"""Node: the composition root wiring store, crypto, and consensus.

Parity target: reference ``Node`` (node/src/node.rs:16-65): read the
committee/secret/parameters files, open the store, start the signature
service, spawn Consensus, and expose (and optionally drain) the commit
channel.

TPU addition: ``verifier_backend`` selects where signature batches are
verified — "cpu" (default) or "tpu" (the JAX batch kernel,
hotstuff_tpu/tpu/ed25519.py) — the SignatureService-boundary plug point
from BASELINE.json.
"""

from __future__ import annotations

import asyncio
import logging

from ..consensus import Consensus, Parameters
from ..crypto import SignatureService
from ..crypto.service import CpuVerifier, VerifierBackend
from ..store import Store
from .config import Secret, read_committee, read_parameters

log = logging.getLogger(__name__)


def make_verifier(kind: str) -> VerifierBackend:
    if kind == "cpu":
        return CpuVerifier()
    if kind == "tpu":
        from ..tpu.ed25519 import BatchVerifier

        return BatchVerifier()
    if kind == "tpu-sharded":
        # batch sharded over every visible device (multi-chip execution;
        # on one chip this degenerates to the plain TPU backend's shape)
        from ..parallel.mesh import ShardedBatchVerifier

        return ShardedBatchVerifier()
    raise ValueError(f"unknown verifier backend '{kind}'")


class Node:
    CHANNEL_CAPACITY = 1_000

    def __init__(self):
        self.commit: asyncio.Queue | None = None
        self.consensus: Consensus | None = None
        self.store: Store | None = None

    @classmethod
    async def new(
        cls,
        committee_file: str,
        key_file: str,
        store_path: str,
        parameters_file: str | None = None,
        verifier_backend: str = "cpu",
        bind_host: str = "0.0.0.0",
    ) -> "Node":
        self = cls()
        committee = read_committee(committee_file)
        secret = Secret.read(key_file)
        parameters = (
            read_parameters(parameters_file) if parameters_file else Parameters()
        )

        self.store = Store(store_path)
        signature_service = SignatureService(secret.secret)
        verifier = make_verifier(verifier_backend)
        if hasattr(verifier, "precompute"):
            # warm the TPU backend's committee point cache (epoch setup)
            verifier.precompute(
                [pk.to_bytes() for pk in committee.authorities]
            )
        committee_size = len(committee.authorities)
        if hasattr(verifier, "warmup") and committee_size >= getattr(
            verifier, "min_device_batch", 0
        ):
            # compile/cache-load the device kernel BEFORE binding the
            # consensus port: a cold compile on the first QC verify
            # would stall past the round timeout and trigger view
            # changes (clients wait for the port, so boot-time cost is
            # invisible to the measured window).  Skipped when every
            # possible batch (<= committee size) routes to the CPU
            # hybrid path anyway — then the kernel is never dispatched.
            verifier.warmup(batch=committee_size)

        self.commit = asyncio.Queue(maxsize=self.CHANNEL_CAPACITY)
        self.consensus = await Consensus.spawn(
            secret.name,
            committee,
            parameters,
            signature_service,
            self.store,
            self.commit,
            verifier=verifier,
            bind_host=bind_host,
        )
        log.info("Node %s successfully booted", secret.name)
        return self

    async def analyze_block(self) -> None:
        """Drain the commit channel — the application layer stub
        (node/src/node.rs:61-65)."""
        while True:
            _block = await self.commit.get()
            # Here the application would execute the committed payload.

    async def shutdown(self) -> None:
        if self.consensus is not None:
            await self.consensus.shutdown()
        if self.store is not None:
            self.store.close()
