"""Node layer: CLI, composition root, JSON config I/O, benchmark client.

Parity map (SURVEY.md §2.5): keys/run/deploy subcommands, Node struct,
Export-style config files, producer-path client — reference crate
``node/``.
"""

from .config import (
    ConfigError,
    Secret,
    read_committee,
    read_parameters,
    write_committee,
    write_parameters,
)
from .node import Node, make_verifier

__all__ = [
    "ConfigError",
    "Secret",
    "read_committee",
    "read_parameters",
    "write_committee",
    "write_parameters",
    "Node",
    "make_verifier",
]
