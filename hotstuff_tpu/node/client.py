"""Benchmark client: an open-loop producer-path load generator.

The reference's client (node/src/client.rs:40-153) still speaks the
deleted mempool's "front" port and can't drive the fork (SURVEY.md §2.5
stale-fork caveat). This client speaks the fork's actual ingest path:
``Producer(Digest)`` messages on the consensus port
(consensus/src/consensus.rs:151-160), round-robining each payload to
``--homes`` live nodes (default 1 — the single-client equivalent of the
reference harness's one-client-per-node topology, local.py:79-91,
keeping proposer queues disjoint so concurrent leaders never fill
blocks with duplicates).

Kept from the reference's methodology (client.rs:103-152):
- wait for every node's port to be listening, then an extra warm-up;
- open-loop rate control in PRECISION bursts per second;
- one tagged sample payload per burst, logged for latency measurement;
- a "rate too high" warning when a burst overruns its slot.

NOTE: the sample log entries are used to compute performance.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from ..crypto import Digest
from ..network.framing import read_frame, set_nodelay, write_frame
from .config import read_committee

log = logging.getLogger("client")

PRECISION = 20  # bursts per second
BURST_INTERVAL = 1.0 / PRECISION
#: deficit catch-up cap, in nominal bursts: a slot that overran leaves a
#: deficit the next slots repay, but a long stall must not turn into one
#: giant burst — beyond this the backlog is forgiven (and the "rate too
#: high" contract line keeps the shortfall honest)
CATCHUP_BURSTS = 8


class _NodeConn:
    """One persistent framed connection; ACK frames are drained.

    A node dying MID-RUN must not kill the client (it feeds the whole
    committee — aborting on one peer's death starves every survivor of
    payloads and stalls consensus; found by the SIGKILL-rejoin e2e).
    Failures mark the connection dead; a background loop reconnects, so
    a restarted node starts receiving payloads again."""

    def __init__(self, address):
        self.address = address
        self.writer: asyncio.StreamWriter | None = None
        self._sink: asyncio.Task | None = None
        self.alive = False

    @staticmethod
    def _reap_orphaned_open(task: "asyncio.Task") -> None:
        """Close a connection whose open completed but whose result was
        dropped by cancellation (no owner will ever see it)."""
        if task.cancelled() or task.exception() is not None:
            return
        _, writer = task.result()
        writer.close()

    async def connect(self) -> None:
        # Cancellation-safe: the caller wraps this in wait_for.  The
        # leak window is the cancel landing AT the await when the open
        # has already completed — the task machinery drops the completed
        # (reader, writer) result, so nothing in this frame ever sees
        # the established transport.  Run the open as its own task and,
        # on cancellation, attach a reaper that closes the transport if
        # the open (has) succeeded; assign self.* only once fully set up.
        open_task = asyncio.ensure_future(
            asyncio.open_connection(*self.address)
        )
        try:
            reader, writer = await open_task
        except asyncio.CancelledError:
            open_task.add_done_callback(self._reap_orphaned_open)
            raise
        try:
            set_nodelay(writer)
            sink = asyncio.ensure_future(self._drain(reader))
        except BaseException:
            writer.close()
            raise
        self.writer = writer
        self._sink = sink
        self.alive = True

    def send_frame(self, message: bytes) -> None:
        if not self.alive:
            return
        try:
            write_frame(self.writer, message)
        except (ConnectionError, OSError):
            self.mark_dead()

    async def drain(self, timeout: float = 1.0) -> None:
        if not self.alive:
            return
        try:
            # a black-holed peer (partition, frozen process — no RST)
            # buffers writes silently until the transport's high-water
            # mark, then drain() would block for the full TCP timeout,
            # starving every LIVE peer of payloads — bound it
            await asyncio.wait_for(self.writer.drain(), timeout)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            self.mark_dead()

    def mark_dead(self) -> None:
        if self.alive:
            log.warning("Node %s:%d unreachable; dropping until it returns",
                        *self.address)
        self.alive = False
        self.close()

    def close(self) -> None:
        if self._sink is not None:
            self._sink.cancel()
            self._sink = None
        if self.writer is not None:
            self.writer.close()
            self.writer = None

    @staticmethod
    async def _drain(reader: asyncio.StreamReader) -> None:
        try:
            while True:
                await read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass


async def wait_for_nodes(
    addresses, poll=0.1, timeout=15.0, expect_faults=0
) -> list:
    """Wait until nodes are listening; give up per-address after
    ``timeout`` so crash-faulted committees (reference local.py:75-76 —
    faulty nodes are simply never booted) don't stall the client.
    ``expect_faults`` is the number of nodes known to never boot: the
    early-start grace below only kicks in once the expected live count
    is reached, so a merely slow-booting node in a fault-free committee
    still gets the full ``timeout``.  Returns the reachable addresses."""
    up = []
    loop = asyncio.get_running_loop()
    last_join = loop.time()

    async def probe(address):
        nonlocal last_join
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            try:
                _, w = await asyncio.open_connection(*address)
                w.close()
                up.append(address)
                last_join = loop.time()
                return
            except OSError:
                await asyncio.sleep(poll)

    # Don't let crash-faulted (never-booted) nodes consume the whole
    # benchmark window: once the expected live count is up and no new
    # node has joined for ``grace`` seconds, start without the rest.
    grace = 2.0
    expected_live = max(1, len(addresses) - expect_faults)
    tasks = [asyncio.ensure_future(probe(a)) for a in addresses]
    deadline = loop.time() + timeout
    while loop.time() < deadline and not all(t.done() for t in tasks):
        await asyncio.sleep(poll)
        if len(up) >= expected_live and loop.time() - last_join > grace:
            break
    for t, a in zip(tasks, addresses):
        if not t.done():
            t.cancel()
            log.warning("Node %s:%d never came up; skipping", *a)
    return up


async def run_client(
    addresses,
    rate: int,
    duration: float,
    warmup: float = 0.0,
    expect_faults: int = 0,
    size: int = 512,
    homes: int = 1,
) -> int:
    """Send ``rate`` producer payloads/s for ``duration`` seconds,
    round-robining each payload to ``homes`` live nodes (see the
    comment at the send loop).  Returns the TOTAL number of payloads
    sent across all nodes.

    ``size``: payload BODY bytes per transaction (default 512, the
    reference's WAN tx size, data/2-chain/README.md:42-57) — the body
    rides the producer message and is stored by the ingest node, so the
    harness measures real byte throughput.  ``size=0`` sends bare
    digests (the fork's original digest-only producer contract).

    ``homes``: how many (consecutive round-robin) nodes receive each
    payload.  1 (default) keeps proposer queues disjoint — maximum
    block capacity, but a payload waits for ITS node's leader turn
    (~half a committee lap of e2e latency at large n).  2+ trades a
    bounded duplicate-proposal window (the proposers prune committed
    digests on every commit signal) for proportionally earlier
    proposal."""
    import os

    from ..consensus.wire import encode_producer

    log.info("Waiting for all nodes to be online...")
    # Boot time scales with committee size when many node processes share
    # few cores (each pays interpreter+import startup): give large
    # committees a proportionally longer grace window.
    boot_timeout = max(15.0, 3.0 * len(addresses))
    live = await wait_for_nodes(
        addresses, timeout=boot_timeout, expect_faults=expect_faults
    )
    if not live:
        log.error("No nodes reachable")
        return 0
    if warmup:
        await asyncio.sleep(warmup)

    conns = [_NodeConn(a) for a in live]
    for c in conns:
        try:
            await asyncio.wait_for(c.connect(), 2.0)
        except (OSError, asyncio.TimeoutError):
            # died between the port probe and here — the reconnector
            # keeps trying; one peer must never kill the whole client
            log.warning("Node %s:%d refused the connection; will retry",
                        *c.address)

    async def try_reconnect(c: _NodeConn) -> None:
        try:
            # bounded: a SYN-black-holing peer must not stall the
            # reconnection of OTHER dead peers for the OS connect timeout
            await asyncio.wait_for(c.connect(), 1.5)
            log.info("Reconnected to %s:%d", *c.address)
        except (OSError, asyncio.TimeoutError):
            pass

    async def reconnector() -> None:
        """Bring dead peers back (a restarted node must start receiving
        payloads again, or it can never propose when it leads)."""
        while True:
            await asyncio.sleep(2.0)
            dead = [c for c in conns if not c.alive]
            if dead:
                await asyncio.gather(*(try_reconnect(c) for c in dead))

    reconnect_task = asyncio.ensure_future(reconnector())

    burst = max(1, rate // PRECISION)
    log.info("Start sending transactions")
    # NOTE: these log entries are used to compute performance.
    log.info("Transactions rate: %d tx/s", rate)
    log.info("Transactions size: %d B", size)

    loop = asyncio.get_running_loop()
    start = loop.time()
    sent = 0
    forgiven = 0  # scheduled payloads written off (dead peers, cap)
    counter = 0
    was_all_dead = False
    try:
        while loop.time() - start < duration:
            slot_start = loop.time()
            # write the whole burst per connection without per-frame
            # drain syncs — one drain per (conn, burst) keeps the client
            # from becoming the bottleneck at large committees (each
            # drain is an await even when the buffer has room).  Send
            # errors mark THAT connection dead (handled inside
            # _NodeConn); the burst continues to the rest.
            # Round-robin each payload to ``homes`` live nodes
            # (default 1: the reference runs one client per node feeding
            # only it, local.py:79-91; this is the single-client
            # equivalent).  Broadcasting every payload to EVERY node
            # makes all proposer queues identical, so concurrent leaders
            # fill blocks with the same digests — measured 3/4 of
            # committed-block capacity wasted on duplicates at 4 nodes;
            # homes=2 measured strictly worse on a one-core host too
            # (docs/ROUND4.md).  With homes=1 queues are disjoint and
            # every block slot unique; orphaned proposals are
            # re-buffered by the proposer (orphan recovery), so
            # single-homing is safe.
            live = [c for c in conns if c.alive]
            # Open-loop integrity: the slot's send count derives from
            # the wall clock, not a fixed quantum — a slot that overran
            # its interval leaves a deficit the following slots repay,
            # so the delivered rate tracks the offered rate instead of
            # silently sagging every time a burst ran long.
            expected = int((slot_start - start) * rate) + burst
            target = expected - sent - forgiven
            if not live:
                # with zero live peers nothing is transmitted: neither
                # the sent counter nor the sample log line may claim
                # otherwise (the harness counts both) — forgive the
                # backlog rather than bursting it all on reconnect
                forgiven += target
                target = 0
            capped = target > burst * CATCHUP_BURSTS
            if capped:
                forgiven += target - burst * CATCHUP_BURSTS
                target = burst * CATCHUP_BURSTS
            for i in range(max(0, target)):
                if size > 0:
                    # real transaction bytes, content-addressed: the
                    # counter makes every body unique (reference
                    # client.rs:103-133 tags bodies with a counter too)
                    body = sent.to_bytes(8, "big") + os.urandom(
                        max(0, size - 8)
                    )
                    digest = Digest.of(body)
                else:
                    body = b""
                    digest = Digest.random()
                if i == 0:
                    # NOTE: this log entry is used to compute performance.
                    log.info("Sending sample payload %s", digest)
                frame = encode_producer(digest, body)
                for h in range(min(homes, len(live))):
                    live[(sent + h) % len(live)].send_frame(frame)
                sent += 1
            for c in conns:
                await c.drain()
            all_dead = not any(c.alive for c in conns)
            if all_dead and not was_all_dead:
                log.warning("Every node unreachable; waiting to reconnect")
            was_all_dead = all_dead
            counter += 1
            elapsed = loop.time() - slot_start
            if capped or elapsed > BURST_INTERVAL:
                # NOTE: this log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            if elapsed < BURST_INTERVAL:
                await asyncio.sleep(BURST_INTERVAL - elapsed)
    finally:
        reconnect_task.cancel()
        for c in conns:
            c.close()
    window = loop.time() - start
    if window > 0:
        # NOTE: this log entry is used to compute performance.
        log.info(
            "Delivered rate: %d tx/s (%d payloads in %.1f s)",
            round(sent / window), sent, window,
        )
    return sent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Producer-path benchmark client"
    )
    parser.add_argument(
        "--committee", required=True, help="committee JSON file"
    )
    parser.add_argument("--rate", type=int, default=1_000, help="payloads/s")
    parser.add_argument(
        "--size",
        type=int,
        default=512,
        help="payload body bytes (0 = digest-only producer contract)",
    )
    parser.add_argument(
        "--homes",
        type=int,
        default=1,
        help="nodes receiving each payload (1 = disjoint queues; more "
        "trades duplicate-proposal slack for earlier proposal)",
    )
    parser.add_argument(
        "--duration", type=float, default=20.0, help="send window (s)"
    )
    parser.add_argument(
        "--warmup", type=float, default=2.0, help="settle time after ports open"
    )
    parser.add_argument(
        "--faults",
        type=int,
        default=0,
        help="nodes known to be crash-faulted (never booted)",
    )
    parser.add_argument("-v", "--verbose", action="count", default=1)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=[logging.ERROR, logging.INFO, logging.DEBUG][min(args.verbose, 2)],
        format="%(asctime)s.%(msecs)03dZ [%(levelname)s] %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )

    from ..consensus.wire import MAX_PAYLOAD_BODY

    if args.homes < 1:
        # fail FAST: homes=0 would count and sample-log payloads that
        # never hit the wire, reporting a silent zero-commit run
        parser.error("--homes must be >= 1")
    if not 0 <= args.size <= MAX_PAYLOAD_BODY:
        # fail FAST: an oversized body would be dropped by every node's
        # wire decoder and the run would silently report zero commits
        parser.error(
            f"--size must be in [0, {MAX_PAYLOAD_BODY}] "
            "(the wire decoder's payload-body cap)"
        )
    if 0 < args.size < 8:
        # the body always carries the 8-byte uniqueness counter, so a
        # 1..7-byte request would silently send 8-byte bodies while the
        # harness reports BPS from the requested size — refuse the
        # misreporting configuration instead
        parser.error("--size must be 0 (digest-only) or >= 8 (counter width)")
    committee = read_committee(args.committee)
    addresses = [a.address for a in committee.authorities.values()]
    sent = asyncio.run(
        run_client(
            addresses,
            args.rate,
            args.duration,
            args.warmup,
            expect_faults=args.faults,
            size=args.size,
            homes=args.homes,
        )
    )
    log.info("Sent %d payloads", sent)
    return 0


if __name__ == "__main__":
    sys.exit(main())
