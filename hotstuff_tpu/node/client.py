"""Benchmark client: an open-loop producer-path load generator.

The reference's client (node/src/client.rs:40-153) still speaks the
deleted mempool's "front" port and can't drive the fork (SURVEY.md §2.5
stale-fork caveat). This client speaks the fork's actual ingest path:
``Producer(Digest)`` messages on the consensus port
(consensus/src/consensus.rs:151-160), broadcast to every node so any
round's leader can propose the payload.

Kept from the reference's methodology (client.rs:103-152):
- wait for every node's port to be listening, then an extra warm-up;
- open-loop rate control in PRECISION bursts per second;
- one tagged sample payload per burst, logged for latency measurement;
- a "rate too high" warning when a burst overruns its slot.

NOTE: the sample log entries are used to compute performance.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from ..crypto import Digest
from ..network.framing import read_frame, set_nodelay, write_frame
from .config import read_committee

log = logging.getLogger("client")

PRECISION = 20  # bursts per second
BURST_INTERVAL = 1.0 / PRECISION


class _NodeConn:
    """One persistent framed connection; ACK frames are drained."""

    def __init__(self, address):
        self.address = address
        self.writer: asyncio.StreamWriter | None = None
        self._sink: asyncio.Task | None = None

    async def connect(self) -> None:
        reader, self.writer = await asyncio.open_connection(*self.address)
        set_nodelay(self.writer)
        self._sink = asyncio.ensure_future(self._drain(reader))

    @staticmethod
    async def _drain(reader: asyncio.StreamReader) -> None:
        try:
            while True:
                await read_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass

    def close(self) -> None:
        if self._sink is not None:
            self._sink.cancel()
        if self.writer is not None:
            self.writer.close()


async def wait_for_nodes(
    addresses, poll=0.1, timeout=15.0, expect_faults=0
) -> list:
    """Wait until nodes are listening; give up per-address after
    ``timeout`` so crash-faulted committees (reference local.py:75-76 —
    faulty nodes are simply never booted) don't stall the client.
    ``expect_faults`` is the number of nodes known to never boot: the
    early-start grace below only kicks in once the expected live count
    is reached, so a merely slow-booting node in a fault-free committee
    still gets the full ``timeout``.  Returns the reachable addresses."""
    up = []
    loop = asyncio.get_running_loop()
    last_join = loop.time()

    async def probe(address):
        nonlocal last_join
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            try:
                _, w = await asyncio.open_connection(*address)
                w.close()
                up.append(address)
                last_join = loop.time()
                return
            except OSError:
                await asyncio.sleep(poll)

    # Don't let crash-faulted (never-booted) nodes consume the whole
    # benchmark window: once the expected live count is up and no new
    # node has joined for ``grace`` seconds, start without the rest.
    grace = 2.0
    expected_live = max(1, len(addresses) - expect_faults)
    tasks = [asyncio.ensure_future(probe(a)) for a in addresses]
    deadline = loop.time() + timeout
    while loop.time() < deadline and not all(t.done() for t in tasks):
        await asyncio.sleep(poll)
        if len(up) >= expected_live and loop.time() - last_join > grace:
            break
    for t, a in zip(tasks, addresses):
        if not t.done():
            t.cancel()
            log.warning("Node %s:%d never came up; skipping", *a)
    return up


async def run_client(
    addresses,
    rate: int,
    duration: float,
    warmup: float = 0.0,
    expect_faults: int = 0,
) -> int:
    """Send ``rate`` producer payloads/s for ``duration`` seconds to every
    node. Returns the number of payloads sent (per node)."""
    from ..consensus.wire import encode_producer

    log.info("Waiting for all nodes to be online...")
    # Boot time scales with committee size when many node processes share
    # few cores (each pays interpreter+import startup): give large
    # committees a proportionally longer grace window.
    boot_timeout = max(15.0, 3.0 * len(addresses))
    live = await wait_for_nodes(
        addresses, timeout=boot_timeout, expect_faults=expect_faults
    )
    if not live:
        log.error("No nodes reachable")
        return 0
    if warmup:
        await asyncio.sleep(warmup)

    conns = [_NodeConn(a) for a in live]
    for c in conns:
        await c.connect()

    burst = max(1, rate // PRECISION)
    log.info("Start sending transactions")
    # NOTE: this log entry is used to compute performance.
    log.info("Transactions rate: %d tx/s", rate)

    loop = asyncio.get_running_loop()
    start = loop.time()
    sent = 0
    counter = 0
    try:
        while loop.time() - start < duration:
            slot_start = loop.time()
            # write the whole burst per connection without per-frame
            # drain syncs — one drain per (conn, burst) keeps the client
            # from becoming the bottleneck at large committees (each
            # drain is an await even when the buffer has room)
            for i in range(burst):
                digest = Digest.random()
                if i == 0:
                    # NOTE: this log entry is used to compute performance.
                    log.info("Sending sample payload %s", digest)
                message = encode_producer(digest)
                for c in conns:
                    write_frame(c.writer, message)
                sent += 1
            for c in conns:
                await c.writer.drain()
            counter += 1
            elapsed = loop.time() - slot_start
            if elapsed > BURST_INTERVAL:
                # NOTE: this log entry is used to compute performance.
                log.warning("Transaction rate too high for this client")
            else:
                await asyncio.sleep(BURST_INTERVAL - elapsed)
    except (ConnectionError, OSError) as e:
        log.error("Failed to send transaction: %s", e)
    finally:
        for c in conns:
            c.close()
    return sent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Producer-path benchmark client"
    )
    parser.add_argument(
        "--committee", required=True, help="committee JSON file"
    )
    parser.add_argument("--rate", type=int, default=1_000, help="payloads/s")
    parser.add_argument(
        "--duration", type=float, default=20.0, help="send window (s)"
    )
    parser.add_argument(
        "--warmup", type=float, default=2.0, help="settle time after ports open"
    )
    parser.add_argument(
        "--faults",
        type=int,
        default=0,
        help="nodes known to be crash-faulted (never booted)",
    )
    parser.add_argument("-v", "--verbose", action="count", default=1)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=[logging.ERROR, logging.INFO, logging.DEBUG][min(args.verbose, 2)],
        format="%(asctime)s.%(msecs)03dZ [%(levelname)s] %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )

    committee = read_committee(args.committee)
    addresses = [a.address for a in committee.authorities.values()]
    sent = asyncio.run(
        run_client(
            addresses,
            args.rate,
            args.duration,
            args.warmup,
            expect_faults=args.faults,
        )
    )
    log.info("Sent %d payloads", sent)
    return 0


if __name__ == "__main__":
    sys.exit(main())
