"""The node CLI: keys / run / deploy.

Parity target: reference ``node/src/main.rs:15-148`` — ``keys`` writes a
fresh keypair file, ``run`` boots a node from config files, ``deploy``
spins up a whole local committee in one process (the in-process testbed,
main.rs:102-148). ``-v`` repeats raise verbosity; millisecond timestamps
are always on (the reference gates them behind the `benchmark` feature —
they're the tracing schema here, SURVEY.md §5).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

from ..consensus import Committee, Parameters
from .config import (
    Secret,
    read_committee,
    write_committee,
    write_parameters,
)
from .node import Node

log = logging.getLogger("node")

LEVELS = [logging.ERROR, logging.WARNING, logging.INFO, logging.DEBUG]


class _FastFormatter(logging.Formatter):
    """The harness log-line format with the per-record strftime cached
    per second: at ~10 load-bearing INFO lines per committed block the
    default Formatter's asctime path (strftime + two %-formats) was a
    measurable slice of the one-core round.  Output is byte-identical
    to the basicConfig format below."""

    def __init__(self):
        super().__init__()
        self._last_sec: int | None = None
        self._last_prefix = ""

    def format(self, record: logging.LogRecord) -> str:
        sec = int(record.created)
        if sec != self._last_sec:
            import time as _time

            self._last_sec = sec
            self._last_prefix = _time.strftime(
                "%Y-%m-%dT%H:%M:%S", _time.localtime(sec)
            )
        msg = record.getMessage()
        if record.exc_info and not record.exc_text:
            record.exc_text = self.formatException(record.exc_info)
        if record.exc_text:
            msg = f"{msg}\n{record.exc_text}"
        if record.stack_info:
            msg = f"{msg}\n{self.formatStack(record.stack_info)}"
        return (
            f"{self._last_prefix}.{int(record.msecs):03d}Z "
            f"[{record.levelname}] {record.name} {msg}"
        )


def setup_logging(verbosity: int) -> None:
    import os

    # HOTSTUFF_LOG_LEVEL overrides the -v count (harness runs pin -vv for
    # the log-scrape contract; this lets an operator crank one run to
    # DEBUG without editing the harness)
    env = os.environ.get("HOTSTUFF_LOG_LEVEL", "")
    level = getattr(logging, env.upper(), None) if env else None
    logging.basicConfig(
        level=level if level is not None else LEVELS[min(verbosity, 3)],
        format="%(asctime)s.%(msecs)03dZ [%(levelname)s] %(name)s %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )
    for handler in logging.getLogger().handlers:
        handler.setFormatter(_FastFormatter())


def _freeze_boot_objects() -> None:
    """Move boot-time immortals (committee state, caches, and — with a
    device verifier — the whole jax runtime) out of the GC's collected
    generations: steady-state collections otherwise scan megabytes of
    permanent objects every pass, which a one-core rig feels directly in
    round latency (measured ~2x consensus-latency cut at 16 nodes)."""
    import gc
    import os

    gc.collect()
    gc.freeze()
    # Full (gen2) collections re-scan every live object and measured
    # 30-55 ms per pause on this rig — a pause that spans ~10 consensus
    # rounds and is the worst mode in the round-gap histogram.  gen0/1
    # keep the default cadence (young garbage is the bulk and collects
    # in ~0.15 ms); gen2 runs 50x less often, turning a per-20 s stall
    # into a per-~15 min one.  Cyclic garbage surviving gen1 accumulates
    # until then — the stretch is paired with a scheduled off-peak full
    # collection below so the accumulation is bounded by the sweep
    # period, not by the (now rare) threshold trigger.
    # HOTSTUFF_GC_GEN2_STRETCH=0 opts out (default thresholds kept) for
    # workloads whose allocation profile is cycle-heavy.
    stretch = os.environ.get("HOTSTUFF_GC_GEN2_STRETCH", "1").strip().lower()
    if stretch in ("", "0", "false", "no", "off"):
        return
    g0, g1, _ = gc.get_threshold()
    gc.set_threshold(g0, g1, 500)
    period = float(os.environ.get("HOTSTUFF_GC_GEN2_PERIOD", "300") or 300)
    if period <= 0:
        return

    async def _gen2_sweep() -> None:
        import time

        glog = logging.getLogger(__name__)
        while True:
            await asyncio.sleep(period)
            t0 = time.perf_counter()
            freed = gc.collect(2)
            glog.debug(
                "scheduled gen2 sweep: %d collected in %.1f ms",
                freed,
                (time.perf_counter() - t0) * 1e3,
            )

    asyncio.ensure_future(_gen2_sweep())


def _metrics_port(args) -> int | None:
    """The /metrics port: ``--metrics-port`` first, then the
    HOTSTUFF_METRICS_PORT env knob; None = endpoint off (default)."""
    port = getattr(args, "metrics_port", None)
    if port is not None:
        return port
    import os

    env = os.environ.get("HOTSTUFF_METRICS_PORT", "").strip()
    if not env:
        return None
    try:
        return int(env)
    except ValueError:
        log.warning("ignoring non-integer HOTSTUFF_METRICS_PORT=%r", env)
        return None


def _apply_journal_dir(args) -> None:
    """Force-enable the flight recorder when ``--journal-dir`` was given
    (the env knobs HOTSTUFF_JOURNAL / HOTSTUFF_JOURNAL_DIR work without
    the flag; off by default)."""
    jdir = getattr(args, "journal_dir", None)
    if jdir:
        from .. import telemetry

        telemetry.set_journal_dir(jdir)


def _apply_profile(args) -> None:
    """Turn the verify-pipeline span profiler on when ``--profile`` was
    given: sets HOTSTUFF_PROFILE (so worker threads and any child
    processes inherit the switch) and force-enables the recorder (env
    check may already have been consumed by an earlier import)."""
    if getattr(args, "profile", False):
        import os

        from .. import telemetry

        os.environ["HOTSTUFF_PROFILE"] = "1"
        telemetry.spans.enable()


def _apply_verify_pipeline(args) -> None:
    """Bridge ``--verify-pipeline N`` into HOTSTUFF_VERIFY_PIPELINE (the
    env-first pattern every other knob uses) so the async verify
    service — and any child node processes — pick the dispatch pipeline
    depth up at service construction."""
    depth = getattr(args, "verify_pipeline", None)
    if depth is not None:
        import os

        os.environ["HOTSTUFF_VERIFY_PIPELINE"] = str(max(1, depth))


def _apply_mesh_devices(args) -> None:
    """Bridge ``--mesh-devices N`` into HOTSTUFF_MESH_DEVICES (the
    env-first pattern) so the sharded verifier sizes its device mesh at
    materialization — in this process and in any child node process the
    deploy path spawns."""
    n = getattr(args, "mesh_devices", None)
    if n is not None:
        import os

        os.environ["HOTSTUFF_MESH_DEVICES"] = str(max(1, n))


def _apply_ingest(args) -> None:
    """Bridge the ingest-plane knobs into their env-first homes:
    ``--max-pending`` -> HOTSTUFF_MAX_PENDING (proposer buffer cap, the
    admission controller's capacity) and ``--ingest-watermark`` ->
    HOTSTUFF_INGEST_WATERMARK (shed threshold as a fraction of that
    cap).  See docs/LOAD.md."""
    import os

    n = getattr(args, "max_pending", None)
    if n is not None:
        os.environ["HOTSTUFF_MAX_PENDING"] = str(max(1, n))
    w = getattr(args, "ingest_watermark", None)
    if w is not None:
        os.environ["HOTSTUFF_INGEST_WATERMARK"] = str(w)


def _apply_health(args) -> None:
    """Activate the live health plane when ``--health`` was given: sets
    HOTSTUFF_HEALTH (env-first, inherited by child node processes) so
    every booted node runs the per-node HealthMonitor
    (telemetry/health.py) — online detectors, ``health.*`` incident
    journal edges, and the bounded campaign recorder."""
    import os

    if getattr(args, "health", False):
        os.environ["HOTSTUFF_HEALTH"] = "1"


def _apply_fresh_state(args) -> None:
    """Bridge ``--fresh-state`` into HOTSTUFF_FRESH_STATE: an explicit
    escape hatch forcing every booted node to discard its persisted
    store.  Normally unnecessary — the committee-hash provenance check
    (node.py) already rejects state from a different committee, and
    matching state is exactly what crash recovery and snapshot
    state-sync want to keep."""
    import os

    if getattr(args, "fresh_state", False):
        os.environ["HOTSTUFF_FRESH_STATE"] = "1"


def _apply_fault_plane(args) -> None:
    """Activate the chaos plane when ``--fault-plane`` was given: the
    flag value (a spec file path or inline JSON) lands in
    HOTSTUFF_FAULTS, which Consensus.spawn reads at boot — exactly the
    env-first pattern the WAN and journal knobs use."""
    import os

    spec = getattr(args, "fault_plane", None)
    if spec:
        os.environ["HOTSTUFF_FAULTS"] = spec


def _apply_adversary(args) -> None:
    """Activate the Byzantine adversary plane when ``--adversary`` was
    given: the flag value (a spec file path or inline JSON naming the
    attacking node indexes and policy windows) lands in
    HOTSTUFF_ADVERSARY, which Consensus.spawn reads at boot.  Inert on
    nodes the spec does not name, so the whole committee can share one
    spec file."""
    import os

    spec = getattr(args, "adversary", None)
    if spec:
        os.environ["HOTSTUFF_ADVERSARY"] = spec


async def _run_node(args) -> None:
    from .. import telemetry

    # before Node.new: a configured endpoint force-enables collection,
    # and the nodes booted below only pick telemetry up at boot
    _apply_journal_dir(args)
    _apply_fault_plane(args)
    _apply_adversary(args)
    _apply_profile(args)
    _apply_verify_pipeline(args)
    _apply_mesh_devices(args)
    _apply_ingest(args)
    _apply_health(args)
    _apply_fresh_state(args)
    await telemetry.maybe_start_server(_metrics_port(args))
    node = await Node.new(
        committee_file=args.committee,
        key_file=args.keys,
        store_path=args.store,
        parameters_file=args.parameters,
        verifier_backend=args.verifier,
        transport=args.transport,
    )
    _freeze_boot_objects()
    # serve() instead of analyze_block(): a node voted out by a
    # committed reconfiguration exits cleanly after its grace window
    await node.serve()


async def _submit_reconfig(args) -> int:
    """Craft, sign, and broadcast a reconfiguration op (docs/RECONFIG.md):
    the NEW epoch's committee (``--new-committee`` file) plus an
    activation margin Δ, sponsored by the member whose key file is
    given.  Every current member receives the op; whichever becomes
    leader first proposes it on-chain."""
    import dataclasses
    import os

    from ..consensus.reconfig import ReconfigOp, newest_epoch
    from ..consensus.wire import encode_reconfig
    from ..crypto import Digest
    from ..crypto.scheme import make_signing_service
    from ..network import SimpleSender

    current = read_committee(args.committee)
    new_com = read_committee(args.new_committee)
    if hasattr(new_com, "entries"):  # a schedule file: its newest epoch
        new_com = new_com.committees()[-1]
    secret = Secret.read(args.keys)
    epoch = (
        args.epoch
        if args.epoch is not None
        else max(new_com.epoch, newest_epoch(current) + 1)
    )
    if epoch != new_com.epoch:
        new_com = dataclasses.replace(new_com, epoch=epoch)
    margin = (
        args.margin
        if args.margin is not None
        else int(os.environ.get("HOTSTUFF_RECONFIG_MARGIN", "8"))
    )
    op = ReconfigOp(new_committee=new_com, margin=margin, sponsor=secret.name)
    service = make_signing_service(secret.scheme, secret.secret)
    op.signature = await service.request_signature(Digest(op.digest()))
    frame = encode_reconfig(op)
    sender = SimpleSender()
    targets = [
        current.address(nm)
        for nm in current.authorities
        if current.address(nm) is not None
    ]
    log.info(
        "Submitting %r (margin %d) to %d current members",
        op,
        margin,
        len(targets),
    )
    for address in targets:
        await sender.send(address, frame)
    # fire-and-forget senders queue frames; give the connections a
    # moment to flush before tearing the process down
    await asyncio.sleep(float(args.linger))
    sender.close()
    return 0


def _raise_fd_limit(target: int) -> None:
    """Best-effort RLIMIT_NOFILE raise to ``target`` (soft AND hard
    when the process may — root on this rig); silently keeps the
    current limit when it is already enough or the raise is denied."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft >= target:
            return
        # Never LOWER the hard cap: RLIM_INFINITY is -1 on Linux, so the
        # obvious max(hard, target) would replace an unlimited cap with
        # ``target`` — and for a non-root process that shrink is
        # irreversible.  Touch the hard cap only when it is finite and
        # actually below the target.
        if hard != resource.RLIM_INFINITY and hard < target:
            new_hard = target
        else:
            new_hard = hard
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (target, new_hard))
        except (ValueError, OSError):
            # can't raise the hard cap: take everything the soft cap allows
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ValueError, OSError, ImportError):
        pass


async def _run_many(args) -> None:
    """Several nodes co-located in ONE process from existing config
    files — the reference's in-process testbed shape (main.rs:102-148)
    driven by the harness's key/committee files.  On a host with fewer
    cores than nodes this removes cross-process scheduling from the
    measured path: every actor shares one asyncio loop."""
    import os

    from .. import telemetry

    _apply_journal_dir(args)
    _apply_fault_plane(args)
    _apply_adversary(args)
    _apply_profile(args)
    _apply_verify_pipeline(args)
    _apply_mesh_devices(args)
    _apply_ingest(args)
    _apply_health(args)
    _apply_fresh_state(args)
    await telemetry.maybe_start_server(_metrics_port(args))
    key_files = args.keys.split(",")
    # Co-location hint: the verifier layer coalesces all these nodes'
    # claims into one device dispatch stream, so the device pays off at
    # committee sizes far below the per-node threshold (node.py warmup).
    os.environ["HOTSTUFF_COLOCATED_NODES"] = str(len(key_files))
    # File-descriptor headroom: n co-located nodes keep one persistent
    # connection per (sender, peer) pair and BOTH socket endpoints live
    # in this process, so a committee-wide timeout broadcast opens up to
    # ~2*n^2 sockets at once (n=256: ~131k — the default 20k limit made
    # a single view-change storm cascade into accept() EMFILE failures
    # and a wedged committee).  Best effort: never lowers the limit and
    # stays inside the hard cap / fs.nr_open.
    _raise_fd_limit(2 * len(key_files) * len(key_files) + 20_000)
    # Where the fd limit cannot cover the committee (a capability-
    # restricted container pins the hard cap), bound the per-sender
    # connection pools instead: idle-LRU eviction keeps the process
    # near (n * senders * cap) connections at 2 fds each, at the cost
    # of reconnects as leadership rotates.  Parity (unbounded) is kept
    # whenever the fd budget already fits the quadratic worst case.
    import resource

    n = len(key_files)
    soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
    if n > 1 and soft < 2 * n * n + 10_000:
        budget_conns = max(1_000, (soft - 4_000) // 2)
        cap = max(4, budget_conns // (4 * n))
        os.environ.setdefault("HOTSTUFF_MAX_PEER_CONNS", str(cap))
        logging.getLogger(__name__).info(
            "fd budget %d < 2*%d^2: bounding per-sender connection "
            "pools at %s",
            soft,
            n,
            os.environ["HOTSTUFF_MAX_PEER_CONNS"],
        )
    nodes = []
    for i, key_file in enumerate(key_files):
        nodes.append(
            await Node.new(
                committee_file=args.committee,
                key_file=key_file,
                store_path=f"{args.store_prefix}{i}",
                parameters_file=args.parameters,
                verifier_backend=args.verifier,
                transport=args.transport,
                bind_host="127.0.0.1",
            )
        )
    _freeze_boot_objects()

    async def _fd_probe() -> None:
        # capacity diagnostics for big co-located committees: one line
        # every 5 s with the process's live fd count (the 256-node fd
        # post-mortem needed exactly this and had to guess)
        plog = logging.getLogger(__name__)
        while True:
            try:
                n_fds = len(os.listdir("/proc/self/fd"))
            except OSError:
                return
            plog.info("fd-probe: %d open fds", n_fds)
            await asyncio.sleep(5)

    probe = None
    if len(nodes) >= 64:
        probe = asyncio.ensure_future(_fd_probe())
    try:
        await asyncio.gather(*(n.serve() for n in nodes))
    finally:
        if probe is not None:
            probe.cancel()


async def _deploy_testbed(
    nodes: int,
    base_port: int,
    scheme: str,
    metrics_port: int | None = None,
    journal_dir: str | None = None,
) -> None:
    """In-process local testbed (reference main.rs:102-148): n fresh
    keypairs, committee.json + node_i.json on disk, every node spawned as
    a task in this process, commit channels drained."""
    from .. import telemetry

    if journal_dir:
        telemetry.set_journal_dir(journal_dir)
    await telemetry.maybe_start_server(metrics_port)
    keys = [Secret.new(scheme) for _ in range(nodes)]
    committee = Committee.new(
        [
            (secret.name, 1, ("127.0.0.1", base_port + i))
            for i, secret in enumerate(keys)
        ],
        scheme=scheme,
        pops={s.name: s.pop for s in keys if s.pop is not None},
    )
    write_committee(committee, ".committee.json")
    write_parameters(Parameters(), ".parameters.json")
    for i, secret in enumerate(keys):
        secret.write(f".node_{i}.json")

    # The testbed's keypairs are FRESH every run, so a leftover .db_*
    # from an earlier deployment can never belong to this committee.
    # No blanket wipe here anymore: Node.new's committee-hash provenance
    # check detects the mismatch and discards the stale store by
    # construction (the "fresh testbed recovers to round ~800" class),
    # while state that DOES match the committee survives for crash
    # recovery and snapshot state-sync.  --fresh-state forces a wipe.
    booted = []
    for i in range(nodes):
        node = await Node.new(
            committee_file=".committee.json",
            key_file=f".node_{i}.json",
            store_path=f".db_{i}",
            parameters_file=".parameters.json",
            bind_host="127.0.0.1",
        )
        booted.append(node)
    log.info("Deployed %d-node local testbed on base port %d", nodes, base_port)
    _freeze_boot_objects()
    await asyncio.gather(*(n.serve() for n in booted))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hotstuff-tpu-node",
        description="A TPU-native implementation of 2-chain HotStuff",
    )
    parser.add_argument("-v", "--verbose", action="count", default=0)
    sub = parser.add_subparsers(dest="command", required=True)

    p_keys = sub.add_parser("keys", help="generate a new keypair file")
    p_keys.add_argument("--filename", required=True)
    p_keys.add_argument(
        "--scheme",
        choices=["ed25519", "bls"],
        default="ed25519",
        help="signature scheme (the committee file records the same "
        "scheme; BLS gives constant-cost aggregate QC verification)",
    )

    p_run = sub.add_parser("run", help="run a node")
    p_run.add_argument("--keys", required=True)
    p_run.add_argument("--committee", required=True)
    p_run.add_argument("--store", required=True)
    p_run.add_argument("--parameters", default=None)
    p_run.add_argument(
        "--transport",
        choices=["asyncio", "native"],
        default="asyncio",
        help="framed-TCP transport: asyncio (default) or the native C++ "
        "epoll reactor (network/native.py)",
    )
    p_run.add_argument(
        "--verifier",
        choices=["cpu", "tpu", "tpu-sharded", "mesh"],
        default="cpu",
        help="signature verification backend ('mesh' is the sharded "
        "multi-chip backend, an alias of tpu-sharded)",
    )
    metrics_help = (
        "serve Prometheus /metrics on this port and enable telemetry "
        "(0 = ephemeral port, logged at startup; default: off, or the "
        "HOTSTUFF_METRICS_PORT env knob)"
    )
    p_run.add_argument(
        "--metrics-port", type=int, default=None, help=metrics_help
    )
    journal_help = (
        "enable the consensus flight recorder and write its JSONL ring "
        "segments under this directory (default: off, or the "
        "HOTSTUFF_JOURNAL / HOTSTUFF_JOURNAL_DIR env knobs; merge "
        "journals with `python -m benchmark traces`)"
    )
    p_run.add_argument("--journal-dir", default=None, help=journal_help)
    profile_help = (
        "enable the verify-pipeline span profiler (ring-buffered "
        "per-stage spans, verify_stage_ms metrics, and — with the "
        "flight recorder on — a 'verify pipeline' Perfetto track; "
        "default: off, or the HOTSTUFF_PROFILE env knob)"
    )
    p_run.add_argument("--profile", action="store_true", help=profile_help)
    faults_help = (
        "activate the chaos plane from this fault-spec file (or inline "
        "JSON): seeded deterministic drop/delay/duplicate/corrupt per "
        "directed peer pair on a scenario timeline (docs/FAULTS.md; "
        "default: off, or the HOTSTUFF_FAULTS env knob)"
    )
    p_run.add_argument("--fault-plane", default=None, help=faults_help)
    adversary_help = (
        "activate the Byzantine adversary plane from this spec file (or "
        "inline JSON): seeded deterministic protocol-level attacks — "
        "equivocate, forge-qc, withhold, double-vote, flood, collude — "
        "on the named node indexes (docs/FAULTS.md; default: off, or "
        "the HOTSTUFF_ADVERSARY env knob)"
    )
    p_run.add_argument("--adversary", default=None, help=adversary_help)
    pipeline_help = (
        "verify dispatch pipeline depth: device waves in flight at once "
        "(default: 2, or the HOTSTUFF_VERIFY_PIPELINE env knob; 1 "
        "restores the single-in-flight dispatch gate)"
    )
    p_run.add_argument(
        "--verify-pipeline",
        type=int,
        default=None,
        metavar="N",
        help=pipeline_help,
    )
    mesh_help = (
        "device count for the sharded mesh verifier (default: every "
        "visible device, or the HOTSTUFF_MESH_DEVICES env knob; only "
        "meaningful with --verifier mesh/tpu-sharded)"
    )
    p_run.add_argument(
        "--mesh-devices", type=int, default=None, metavar="N", help=mesh_help
    )
    max_pending_help = (
        "proposer payload buffer cap / ingest admission capacity "
        "(default 100000, or the HOTSTUFF_MAX_PENDING env knob)"
    )
    watermark_help = (
        "buffer-occupancy fraction above which the ingest plane sheds "
        "producer payloads with a typed BUSY reply (default 0.75, or "
        "HOTSTUFF_INGEST_WATERMARK)"
    )
    p_run.add_argument(
        "--max-pending",
        type=int,
        default=None,
        metavar="N",
        help=max_pending_help,
    )
    fresh_state_help = (
        "discard any persisted store before booting (escape hatch; by "
        "default matching state is recovered and mismatched-committee "
        "state is rejected by the provenance check)"
    )
    p_run.add_argument(
        "--ingest-watermark",
        type=float,
        default=None,
        metavar="F",
        help=watermark_help,
    )
    health_help = (
        "enable the live health plane: per-node online anomaly "
        "detectors (leader-stall, view-change storm, commit collapse, "
        "shed storm), health.* incident journal edges, the /delta "
        "streaming-export route, and the bounded campaign recorder "
        "(docs/TELEMETRY.md; default: off, or the HOTSTUFF_HEALTH env "
        "knob)"
    )
    p_run.add_argument("--health", action="store_true", help=health_help)
    p_run.add_argument(
        "--fresh-state", action="store_true", help=fresh_state_help
    )

    p_many = sub.add_parser(
        "run-many",
        help="run several nodes in one process from existing config files",
    )
    p_many.add_argument("--keys", required=True, help="comma-separated key files")
    p_many.add_argument("--committee", required=True)
    p_many.add_argument("--store-prefix", required=True)
    p_many.add_argument("--parameters", default=None)
    p_many.add_argument(
        "--transport", choices=["asyncio", "native"], default="asyncio"
    )
    p_many.add_argument(
        "--verifier",
        choices=["cpu", "tpu", "tpu-sharded", "mesh"],
        default="cpu",
    )
    p_many.add_argument(
        "--metrics-port", type=int, default=None, help=metrics_help
    )
    p_many.add_argument("--journal-dir", default=None, help=journal_help)
    p_many.add_argument("--profile", action="store_true", help=profile_help)
    p_many.add_argument("--fault-plane", default=None, help=faults_help)
    p_many.add_argument("--adversary", default=None, help=adversary_help)
    p_many.add_argument(
        "--verify-pipeline",
        type=int,
        default=None,
        metavar="N",
        help=pipeline_help,
    )
    p_many.add_argument(
        "--mesh-devices", type=int, default=None, metavar="N", help=mesh_help
    )
    p_many.add_argument(
        "--max-pending",
        type=int,
        default=None,
        metavar="N",
        help=max_pending_help,
    )
    p_many.add_argument(
        "--ingest-watermark",
        type=float,
        default=None,
        metavar="F",
        help=watermark_help,
    )
    p_many.add_argument("--health", action="store_true", help=health_help)
    p_many.add_argument(
        "--fresh-state", action="store_true", help=fresh_state_help
    )

    p_rec = sub.add_parser(
        "reconfig",
        help="submit a signed committee reconfiguration to the live "
        "committee (docs/RECONFIG.md)",
    )
    p_rec.add_argument(
        "--keys",
        required=True,
        help="key file of the sponsoring CURRENT member",
    )
    p_rec.add_argument(
        "--committee",
        required=True,
        help="the current committee (or schedule) file — submission "
        "targets and epoch numbering",
    )
    p_rec.add_argument(
        "--new-committee",
        required=True,
        help="committee file holding the NEXT epoch's full membership",
    )
    p_rec.add_argument(
        "--margin",
        type=int,
        default=None,
        metavar="N",
        help="activation margin Δ in rounds after the commit (default "
        "8, or the HOTSTUFF_RECONFIG_MARGIN env knob)",
    )
    p_rec.add_argument(
        "--epoch",
        type=int,
        default=None,
        metavar="N",
        help="override the new committee's epoch number (default: "
        "newest known epoch + 1)",
    )
    p_rec.add_argument(
        "--linger",
        type=float,
        default=1.0,
        metavar="S",
        help="seconds to keep the submission connections open (flush)",
    )

    p_dep = sub.add_parser("deploy", help="deploy a local testbed")
    p_dep.add_argument("--nodes", type=int, required=True)
    p_dep.add_argument("--base-port", type=int, default=25_200)
    p_dep.add_argument(
        "--scheme", choices=["ed25519", "bls"], default="ed25519"
    )
    p_dep.add_argument(
        "--metrics-port", type=int, default=None, help=metrics_help
    )
    p_dep.add_argument("--journal-dir", default=None, help=journal_help)
    p_dep.add_argument("--profile", action="store_true", help=profile_help)
    p_dep.add_argument("--fault-plane", default=None, help=faults_help)
    p_dep.add_argument("--adversary", default=None, help=adversary_help)
    p_dep.add_argument(
        "--verify-pipeline",
        type=int,
        default=None,
        metavar="N",
        help=pipeline_help,
    )
    p_dep.add_argument(
        "--mesh-devices", type=int, default=None, metavar="N", help=mesh_help
    )
    p_dep.add_argument(
        "--max-pending",
        type=int,
        default=None,
        metavar="N",
        help=max_pending_help,
    )
    p_dep.add_argument(
        "--ingest-watermark",
        type=float,
        default=None,
        metavar="F",
        help=watermark_help,
    )
    p_dep.add_argument("--health", action="store_true", help=health_help)
    p_dep.add_argument(
        "--fresh-state", action="store_true", help=fresh_state_help
    )

    args = parser.parse_args(argv)
    setup_logging(args.verbose)

    if args.command == "keys":
        Secret.new(args.scheme).write(args.filename)
        return 0
    if args.command == "run":
        # sanity-check the committee file before booting
        read_committee(args.committee)
        asyncio.run(_run_node(args))
        return 0
    if args.command == "run-many":
        read_committee(args.committee)
        asyncio.run(_run_many(args))
        return 0
    if args.command == "reconfig":
        return asyncio.run(_submit_reconfig(args))
    if args.command == "deploy":
        _apply_fault_plane(args)
        _apply_adversary(args)
        _apply_profile(args)
        _apply_verify_pipeline(args)
        _apply_mesh_devices(args)
        _apply_ingest(args)
        _apply_health(args)
        _apply_fresh_state(args)
        asyncio.run(
            _deploy_testbed(
                args.nodes,
                args.base_port,
                args.scheme,
                metrics_port=_metrics_port(args),
                journal_dir=getattr(args, "journal_dir", None),
            )
        )
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
