"""Verify-pipeline span profiler: where a claim wave's wall time goes.

``BENCH_r05.json`` shows the QC-256 verify at ~0.46 ms on-device but
~91 ms p50 end-to-end on the rig — a ~180x host-side gap that neither
the metric counters (ISSUE 1), the flight recorder (ISSUE 2), nor the
chaos plane (ISSUE 3) can attribute to a *stage*.  This module is the
missing instrument: a ring-buffered span recorder the verify pipeline
threads through every hop from claim arrival to device readback.

Span taxonomy (leaf stages sum to the wave's end-to-end time)::

    coalesce.wait    first submit -> the dispatcher collects the batch
    route.decide     the device-vs-CPU routing decision
    stage.pack       wave padding to the fixed bucket shape (ISSUE 6)
    stage.slot_wait  dispatch-loop handoff -> slot thread entry
    queue.wait       executor handoff -> worker thread entry (legacy
                     executor paths; the dispatch loop emits
                     stage.slot_wait instead)
    flatten          claims -> flat (digest, pk, sig) arrays
    prepare          host staging: decompress lookup, hashing, padding
    dispatch         kernel call (device enqueue; returns a future)
    device.execute   block_until_ready on the enqueued computation
    mesh.psum        mesh backend only: fetching the replicated QC-valid
                     word — the single psum crossing ICI (ISSUE 7); when
                     it reads 0 the sharded lane gather is skipped
    readback         device -> host transfer of the verdict lanes
    host.verify      CPU evaluation (inline route / fallback / hybrid)
    host.pairing     BLS pairing equality on the host
    verdict.fanout   worker completion -> every waiter's future resolved

plus parent spans (``e2e``, ``dispatch.wall``, ``agg.verify``,
``scheme.route``) that frame the leaves but are excluded from waterfall
sums — ``benchmark/profile.py`` renders the per-stage waterfall and its
coverage of the measured end-to-end latency.

Design constraints (same contract as the journal):

- **Off by default.**  ``HOTSTUFF_PROFILE=1`` / ``--profile`` /
  :func:`enable` turn it on.  Disabled, :func:`span` returns one shared
  no-op context manager and :func:`recorder` returns ``None`` — no
  allocation, no clock reads, a single module-global test per call
  site (asserted < 2% of a 1k-claim wave in tests/test_profile.py).
- **Bounded.**  Completed spans land in a ``deque(maxlen=capacity)``
  ring (default 65536): a run that outlives the ring loses its OLDEST
  spans, a flight recorder, not an archive.
- **Thread-correct.**  The dispatcher's event loop and the verify
  worker thread both record; ``perf_counter_ns`` is CLOCK_MONOTONIC
  (cross-thread consistent) and per-thread nesting depth lives in a
  ``threading.local``.

Fan-out when a span completes (both optional, both pull their switches
once): a ``verify_stage_ms{stage=...}`` histogram in the telemetry
registry, and — when a journal is attached — an ``{"e":"span"}`` record
whose ``u`` field carries the duration, rendered by
``benchmark/traces.py`` as a per-node "verify pipeline" Perfetto track
aligned with the consensus rounds.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import nullcontext

from .taxonomy import (
    SPAN_ANNOTATION_STAGES as ANNOTATION_STAGES,
    SPAN_LEAF_STAGES as LEAF_STAGES,
    SPAN_PARENT_STAGES as PARENT_STAGES,
)

DEFAULT_CAPACITY = 65536

#: stage-duration histogram bounds in MILLISECONDS: 1 us doubling up to
#: ~134 s — one ladder below the consensus LATENCY_BOUNDS_S so sub-0.1 ms
#: device stages (dispatch ~50 us) don't collapse into the first bucket
STAGE_BOUNDS_MS: tuple[float, ...] = tuple(1e-3 * 2**i for i in range(28))

# the stage tables themselves (leaf pipeline order, parent frames, value
# annotations) live in telemetry/taxonomy.py — the one registry the
# analysis plane lints against and benchmark/traces.py renders from;
# the LEAF_STAGES / PARENT_STAGES / ANNOTATION_STAGES re-exports above
# keep benchmark/profile.py and existing call sites working unchanged

_RECORDER: "SpanRecorder | None" = None
_ENV_CHECKED = False
_SINK = None  # journal fan-out: fn(stage, dur_ns), set via attach_journal
_NULL = nullcontext()  # the shared disabled-path context (reentrant)


def _env_on() -> bool:
    env = os.environ.get("HOTSTUFF_PROFILE")
    return env is not None and env.strip().lower() not in (
        "", "0", "false", "no", "off",
    )


def recorder() -> "SpanRecorder | None":
    """The live recorder, or None when profiling is off.  Call sites
    guard manual timing with ``rec = spans.recorder(); if rec is not
    None: ...`` — the disabled path is one global read (plus a one-time
    env check the first call pays)."""
    global _RECORDER, _ENV_CHECKED
    if _RECORDER is not None:
        return _RECORDER
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        if _env_on():
            _RECORDER = SpanRecorder()
    return _RECORDER


def span(name: str):
    """``with spans.span("prepare"): ...`` — a timed span when profiling
    is on, the shared no-op context otherwise (no allocation)."""
    rec = recorder()
    return _NULL if rec is None else rec.span(name)


def enabled() -> bool:
    return recorder() is not None


def enable(capacity: int = DEFAULT_CAPACITY) -> "SpanRecorder":
    """Force-enable profiling (the CLI's --profile and the profile
    bench call this); idempotent — an existing recorder is kept."""
    global _RECORDER
    if _RECORDER is None:
        _RECORDER = SpanRecorder(capacity)
    return _RECORDER


def disable() -> None:
    """Drop the recorder and re-arm the env check (tests)."""
    global _RECORDER, _ENV_CHECKED, _SINK
    _RECORDER = None
    _ENV_CHECKED = False
    _SINK = None


def attach_journal(journal) -> None:
    """Fan completed spans out into ``journal`` as ``{"e":"span"}``
    records (stage in ``p``, duration ns in ``u``).  First journal wins:
    spans are process-wide (the verify service is shared across a
    co-located committee), so the whole pipeline renders as ONE track
    pinned to the first journaled node."""
    global _SINK
    if _SINK is None and journal is not None:
        _SINK = lambda stage, dur_ns: journal.record(
            "span", 0, None, stage, dur_ns=dur_ns
        )


class _Span:
    """One live span (context manager).  Cheap by construction: two
    clock reads, a thread-local depth bump, one ring append on exit."""

    __slots__ = ("_rec", "name", "t0", "depth")

    def __init__(self, rec: "SpanRecorder", name: str):
        self._rec = rec
        self.name = name
        self.t0 = 0
        self.depth = 0

    def __enter__(self) -> "_Span":
        local = self._rec._local
        self.depth = getattr(local, "depth", 0)
        local.depth = self.depth + 1
        self.t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> None:
        dur = time.perf_counter_ns() - self.t0
        self._rec._local.depth = self.depth
        self._rec._emit(self.name, self.t0, dur, self.depth)


class SpanRecorder:
    """Ring buffer of completed spans ``(name, t0_ns, dur_ns, depth,
    thread)`` with optional metric/journal fan-out."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._local = threading.local()
        self.spans_total = 0
        # None = undecided (checked on the first span so tests that
        # enable telemetry before profiling are seen); False = off
        self._metrics_on: bool | None = None
        self._hists: dict[str, object] = {}

    # ---- recording -------------------------------------------------------

    def span(self, name: str) -> _Span:
        return _Span(self, name)

    def add(self, name: str, t0_ns: int, dur_ns: int) -> None:
        """A manually-timed span (stages whose start predates the code
        that can observe them, e.g. coalesce.wait from submit stamps)."""
        self._emit(name, t0_ns, max(0, int(dur_ns)), 0)

    def _emit(self, name: str, t0_ns: int, dur_ns: int, depth: int) -> None:
        self._ring.append(
            (name, t0_ns, dur_ns, depth, threading.current_thread().name)
        )
        self.spans_total += 1
        if self._metrics_on is None:
            from hotstuff_tpu import telemetry

            self._metrics_on = telemetry.enabled()
        if self._metrics_on:
            hist = self._hists.get(name)
            if hist is None:
                from hotstuff_tpu import telemetry

                hist = self._hists[name] = telemetry.registry().histogram(
                    "verify_stage_ms",
                    "Verify-pipeline stage durations (milliseconds)",
                    {"stage": name},
                    bounds=STAGE_BOUNDS_MS,
                )
            # annotation stages carry a value in the dur field, not
            # nanoseconds — observe it raw (e.g. in-flight wave depth)
            hist.observe(
                dur_ns if name in ANNOTATION_STAGES else dur_ns / 1e6
            )
        sink = _SINK
        if sink is not None:
            try:
                sink(name, dur_ns)
            except Exception:  # noqa: BLE001 — profiling must never kill
                pass  # the pipeline it observes

    # ---- draining --------------------------------------------------------

    def snapshot(self) -> list[tuple]:
        return list(self._ring)

    def drain(self) -> list[tuple]:
        out = list(self._ring)
        self._ring.clear()
        return out

    def stats(self) -> dict:
        return {
            "spans": self.spans_total,
            "buffered": len(self._ring),
            "capacity": self.capacity,
            "dropped": max(0, self.spans_total - self.capacity),
        }


__all__ = [
    "SpanRecorder",
    "DEFAULT_CAPACITY",
    "STAGE_BOUNDS_MS",
    "LEAF_STAGES",
    "PARENT_STAGES",
    "ANNOTATION_STAGES",
    "recorder",
    "span",
    "enabled",
    "enable",
    "disable",
    "attach_journal",
]
