"""Wire-level flow accounting (ISSUE 19).

Every frame that crosses a link is charged — at its send site and at
its receive site — to a ``(peer, direction, message_class)`` flow.  The
message class is derived from the frame's first byte (the wire-tag
taxonomy of ``consensus/wire.py``); the class list itself is registered
in ``telemetry/taxonomy.py`` (``FLOW_CLASSES``) so the taxonomy lint
covers it, and ``tests/test_flows.py`` cross-checks the byte->class map
against the live wire constants so tag drift is a test failure instead
of a silently-mislabelled flow.

The accountant is a pure-Python counter table with no lock on the hot
path beyond one ``dict`` update per frame (every transport drives it
from the node's event loop).  A frame's wire cost is always
``FRAME_OVERHEAD + len(payload)`` — the u32 length prefix of
``network/framing.py`` / ``native/transport.cpp`` plus the payload —
so accounted bytes equal the exact encoded frame length.

Two byte ledgers per node:

- **wire** bytes per ``(peer, dir, class)`` flow: what actually crossed
  (or arrived from) each link, retransmissions included and ALSO
  tallied separately (``retx``) so amplification is never conflated
  with retry overhead;
- **logical** bytes per class: one frame charged per public
  ``send``/``broadcast`` API call, regardless of fan-out.  The ratio
  ``wire / logical`` per class is the node's amplification factor —
  a leader's ``propose`` broadcast to n-1 followers reads exactly
  ``n-1``.

Determinism: the table is insertion-ordered plain data and every charge
is driven by the transport's own (virtual-time in sim) scheduling, so a
same-seed sim double-run produces byte-identical flow tables —
``SimVerdict.flows`` asserts it.

Knobs: ``HOTSTUFF_NET`` (set to ``0`` to disable accounting),
``HOTSTUFF_NET_TOPK`` (peers exported per snapshot, default 8, the rest
folded into an explicit ``peers_elided`` count — no silent caps),
``HOTSTUFF_NET_SAMPLE`` (journal a ``net.tx``/``net.rx`` cumulative
byte record every Nth accounting event, default 64; 0 disables).
"""

from __future__ import annotations

import os

#: u32 length prefix bytes prepended to every payload on the wire
#: (network/framing.py ``_LEN`` / native/transport.cpp ``frame_into``)
FRAME_OVERHEAD = 4

#: first wire byte -> message class.  Tag values mirror
#: consensus/wire.py (TAG_PROPOSE..TAG_RECONFIG, ACK[0], INGEST_ACK_TAG,
#: STATE_VALUE_TAG); kept as literals so this module stays a telemetry
#: leaf with no consensus import — tests/test_flows.py pins the parity.
_TAG_CLASS: dict = {
    0: "propose",
    1: "vote",
    2: "timeout",
    3: "tc",
    4: "sync-req",
    5: "producer-v1",
    6: "producer-v2",
    7: "state-sync",  # TAG_STATE_REQUEST
    8: "state-sync",  # TAG_STATE_MANIFEST
    9: "state-sync",  # TAG_STATE_CHUNK
    10: "state-sync",  # TAG_STATE_READ
    11: "reconfig",
    0x41: "ack",  # ACK = b"Ack"
    0xA2: "ingest-ack",  # INGEST_ACK_TAG
    0xA3: "state-sync",  # STATE_VALUE_TAG (state-read reply)
}


def frame_class(payload: bytes) -> str:
    """Message class of one wire payload (its first byte's tag family);
    ``"other"`` for unknown tags and empty frames — every frame lands in
    exactly one registered class, so per-class shares always cover 100%
    of accounted bytes."""
    if not payload:
        return "other"
    return _TAG_CLASS.get(payload[0], "other")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FlowAccounting:
    """Per-node wire/logical byte ledgers.

    One instance per node process (the sim gives each in-process node
    its own, like its private telemetry Registry).  Transports call
    :meth:`tx` / :meth:`rx` / :meth:`retx` with the raw payload at the
    moment bytes actually cross; public sender APIs call
    :meth:`logical` once per send/broadcast call.
    """

    def __init__(self, node: str = "", enabled: bool | None = None):
        self.node = node
        if enabled is None:
            enabled = os.environ.get("HOTSTUFF_NET", "1") not in (
                "0",
                "false",
                "off",
            )
        self.enabled = enabled
        self.topk = _env_int("HOTSTUFF_NET_TOPK", 8)
        self.sample = _env_int("HOTSTUFF_NET_SAMPLE", 64)
        #: (peer, dir, class) -> [wire_bytes, frames, retx_bytes,
        #: retx_frames]
        self._flows: dict[tuple[str, str, str], list[int]] = {}
        #: class -> [logical_bytes, logical_frames]
        self._logical: dict[str, list[int]] = {}
        #: address -> peer label (committee names where known); the
        #: fallback label is the address's host component
        self._labels: dict = {}
        self._events = 0
        #: journal provider: a zero-arg callable returning the node's
        #: journal (or None) — bound by NodeTelemetry.attach_flows so
        #: the journal can attach after the accountant
        self._journal_fn = None

    # ---- wiring ----------------------------------------------------------

    def label_peers(self, pairs) -> None:
        """Register committee peer labels: ``pairs`` is an iterable of
        ``(name, address)``.  Unlabelled addresses degrade to their host
        component — attribution is then per-host, never dropped."""
        for name, address in pairs:
            self._labels[address] = name

    def bind_journal(self, journal_fn) -> None:
        self._journal_fn = journal_fn

    def peer_label(self, address) -> str:
        label = self._labels.get(address)
        if label is not None:
            return label
        if isinstance(address, tuple) and address:
            return str(address[0])
        return str(address)

    # ---- hot path --------------------------------------------------------

    def _row(self, peer: str, direction: str, cls: str) -> list[int]:
        key = (peer, direction, cls)
        row = self._flows.get(key)
        if row is None:
            row = self._flows[key] = [0, 0, 0, 0]
        return row

    def _note(self, direction: str, cls: str, total: int) -> None:
        self._events += 1
        if not self.sample or self._events % self.sample:
            return
        fn = self._journal_fn
        j = fn() if fn is not None else None
        if j is not None:
            # class rides the peer field, cumulative direction bytes in
            # the value field — the Perfetto net lanes render both
            j.record(f"net.{direction}", peer=cls, dur_ns=total)

    def tx(self, address, payload: bytes, retx: bool = False) -> None:
        """Charge one frame actually written toward ``address`` (called
        at the transmit site, after fault decisions — a dropped frame is
        never charged, a corrupted one is: its bytes hit the wire)."""
        if not self.enabled:
            return
        cls = frame_class(payload)
        wire = FRAME_OVERHEAD + len(payload)
        row = self._row(self.peer_label(address), "tx", cls)
        row[0] += wire
        row[1] += 1
        if retx:
            row[2] += wire
            row[3] += 1
        self._note("tx", cls, self.tx_bytes())

    def rx(self, peer, payload: bytes) -> None:
        """Charge one frame read off a link (``peer`` is the remote
        peername; ephemeral client ports carry no identity, so receive
        flows attribute per remote host)."""
        if not self.enabled:
            return
        cls = frame_class(payload)
        row = self._row(self.peer_label(peer), "rx", cls)
        row[0] += FRAME_OVERHEAD + len(payload)
        row[1] += 1
        self._note("rx", cls, self.rx_bytes())

    def logical(self, payload: bytes, calls: int = 1) -> None:
        """Charge one API-level message (a ``send`` or a whole
        ``broadcast``): the denominator of the amplification factor."""
        if not self.enabled:
            return
        cls = frame_class(payload)
        row = self._logical.get(cls)
        if row is None:
            row = self._logical[cls] = [0, 0]
        row[0] += calls * (FRAME_OVERHEAD + len(payload))
        row[1] += calls

    # ---- derived views ---------------------------------------------------

    def tx_bytes(self) -> int:
        return sum(
            r[0] for (_, d, _c), r in self._flows.items() if d == "tx"
        )

    def rx_bytes(self) -> int:
        return sum(
            r[0] for (_, d, _c), r in self._flows.items() if d == "rx"
        )

    def retx_bytes(self) -> int:
        return sum(
            r[2] for (_, d, _c), r in self._flows.items() if d == "tx"
        )

    def class_totals(self) -> dict:
        """class -> {tx_bytes, tx_frames, rx_bytes, rx_frames,
        retx_bytes, retx_frames}, sorted by class name."""
        out: dict = {}
        for (_peer, d, cls), row in self._flows.items():
            ent = out.setdefault(
                cls,
                {
                    "tx_bytes": 0,
                    "tx_frames": 0,
                    "rx_bytes": 0,
                    "rx_frames": 0,
                    "retx_bytes": 0,
                    "retx_frames": 0,
                },
            )
            ent[f"{d}_bytes"] += row[0]
            ent[f"{d}_frames"] += row[1]
            if d == "tx":
                ent["retx_bytes"] += row[2]
                ent["retx_frames"] += row[3]
        return {cls: out[cls] for cls in sorted(out)}

    def amplification(self) -> dict:
        """class -> wire-egress / logical-egress byte ratio, for classes
        with any logical bytes charged.  A propose broadcast to n-1
        followers reads n-1; retransmissions push a class above its
        fan-out (which is the point of keeping retx separate)."""
        tx_by_cls: dict[str, int] = {}
        for (_peer, d, cls), row in self._flows.items():
            if d == "tx":
                tx_by_cls[cls] = tx_by_cls.get(cls, 0) + row[0]
        return {
            cls: round(tx_by_cls.get(cls, 0) / logical[0], 3)
            for cls, logical in sorted(self._logical.items())
            if logical[0]
        }

    def peer_totals(self) -> list[tuple[str, int, int]]:
        """(peer, tx_bytes, rx_bytes) sorted by total bytes descending
        (ties by name, so the ordering is deterministic)."""
        by_peer: dict[str, list[int]] = {}
        for (peer, d, _cls), row in self._flows.items():
            ent = by_peer.setdefault(peer, [0, 0])
            ent[0 if d == "tx" else 1] += row[0]
        return sorted(
            ((p, tx, rx) for p, (tx, rx) in by_peer.items()),
            key=lambda e: (-(e[1] + e[2]), e[0]),
        )

    def table(self) -> dict:
        """The full JSON-stable flow table (the sim determinism
        artifact): integer ledgers only, keys sorted."""
        return {
            "flows": {
                f"{peer}|{d}|{cls}": list(row)
                for (peer, d, cls), row in sorted(self._flows.items())
            },
            "logical": {
                cls: list(row)
                for cls, row in sorted(self._logical.items())
            },
        }

    def snapshot(self) -> dict:
        """The ``flows`` telemetry section (pull-model; lands in the
        node's snapshot log line, /metrics export and the /delta
        stream).  Peers beyond the top-K by bytes are folded into an
        explicit ``peers_elided`` count — never silently dropped."""
        if not self.enabled:
            return {"enabled": False}
        retx_b = retx_f = tx_f = rx_f = 0
        for (_p, d, _c), row in self._flows.items():
            if d == "tx":
                tx_f += row[1]
                retx_b += row[2]
                retx_f += row[3]
            else:
                rx_f += row[1]
        peers = self.peer_totals()
        shown = peers[: self.topk] if self.topk > 0 else peers
        return {
            "enabled": True,
            "tx_bytes": self.tx_bytes(),
            "rx_bytes": self.rx_bytes(),
            "tx_frames": tx_f,
            "rx_frames": rx_f,
            "retx_bytes": retx_b,
            "retx_frames": retx_f,
            "classes": self.class_totals(),
            "amp": self.amplification(),
            "peers": {
                p: {"tx_bytes": tx, "rx_bytes": rx}
                for p, tx, rx in shown
            },
            "peers_elided": max(0, len(peers) - len(shown)),
        }


__all__ = ["FRAME_OVERHEAD", "FlowAccounting", "frame_class"]
