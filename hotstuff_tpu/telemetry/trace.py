"""Per-round block-lifecycle trace recorder.

Timestamps the edges a block crosses on its way to commit — as seen by
THIS node (every node runs its own recorder; the harness compares them
across logs):

    payload-received .. proposed      (payload_wait, observed by the
                                       proposer at make time)
    proposed -> first-vote            (propose_to_vote)
    first-vote -> QC-formed           (vote_to_qc)
    QC-formed -> committed            (qc_to_commit)
    proposed -> committed             (propose_to_commit, the end-to-end
                                       per-block consensus latency)

plus the view-change edges (local timeouts, TC-driven round advances,
round gaps across a view change).

Hot-path cost model: one ``mark_*`` is a dict lookup plus scalar writes
into a preallocated 5-slot record; the record itself (one small list) is
allocated once per *proposal*, never per message or per signature.  The
open-record map and the completed-round ring are both bounded, so a
flood of never-committing proposals cannot grow memory.
"""

from __future__ import annotations

import time
from collections import OrderedDict, deque

from .metrics import Registry

# Open-record slots (one list per block in flight).
_ROUND = 0
_T_PROPOSED = 1
_T_VOTE = 2
_T_QC = 3

#: lifecycle edges reported per committed block, in causal order
EDGES = ("propose_to_vote", "vote_to_qc", "qc_to_commit", "propose_to_commit")

#: open records kept (proposals whose fate is undecided)
DEFAULT_CAPACITY = 4_096
#: completed per-round records kept for inspection (the ring buffer)
DEFAULT_RING = 256


class TraceRecorder:
    """Bounded per-block lifecycle recorder + per-edge histograms.

    ``labels`` (typically ``{"node": <id>}``) distinguish co-located
    nodes sharing one process-wide registry.
    """

    def __init__(
        self,
        registry: Registry,
        labels: dict | None = None,
        capacity: int = DEFAULT_CAPACITY,
        ring: int = DEFAULT_RING,
        clock=time.monotonic,
    ):
        labels = labels or {}
        self._clock = clock
        self._capacity = capacity
        # digest bytes -> [round, t_proposed, t_first_vote, t_qc_formed]
        self._open: OrderedDict[bytes, list] = OrderedDict()
        # completed round records, newest last (bounded ring)
        self.ring: deque = deque(maxlen=ring)
        self.hist = {
            edge: registry.histogram(
                "commit_edge_seconds",
                "Block lifecycle edge latency as seen by this node",
                {**labels, "edge": edge},
            )
            for edge in EDGES
        }
        self.payload_wait = registry.histogram(
            "payload_wait_seconds",
            "Payload buffered at the proposer before entering a block",
            dict(labels),
        )
        self.commits = registry.counter(
            "committed_blocks_total", "Blocks committed", dict(labels)
        )
        self.timeouts = registry.counter(
            "local_timeouts_total", "Local round timeouts fired", dict(labels)
        )
        self.tcs = registry.counter(
            "tc_advances_total", "Round advances driven by a TC", dict(labels)
        )
        self.round_gap = registry.histogram(
            "commit_round_gap",
            "Rounds between consecutive commits (1 = no view change)",
            dict(labels),
            bounds=tuple(float(2**i) for i in range(10)),
        )
        self._last_commit_round = 0

    # ---- lifecycle marks (hot path) ------------------------------------

    def mark_proposed(self, digest: bytes, round_: int) -> None:
        """First sighting of a (verified) proposal for ``round_``."""
        if digest in self._open:
            return
        if len(self._open) >= self._capacity:
            self._open.popitem(last=False)
        self._open[digest] = [round_, self._clock(), 0.0, 0.0]

    def mark_first_vote(self, digest: bytes) -> None:
        rec = self._open.get(digest)
        if rec is not None and not rec[_T_VOTE]:
            rec[_T_VOTE] = self._clock()

    def mark_qc_formed(self, digest: bytes) -> None:
        rec = self._open.get(digest)
        if rec is not None and not rec[_T_QC]:
            rec[_T_QC] = self._clock()

    def mark_committed(self, digest: bytes, round_: int = 0) -> None:
        now = self._clock()
        rec = self._open.pop(digest, None)
        self.commits.inc()
        if rec is None:
            # committed via chain walk without ever being seen as a
            # proposal (sync'd ancestor) — count it, no edge samples
            return
        round_ = rec[_ROUND] or round_
        if self._last_commit_round:
            self.round_gap.observe(float(round_ - self._last_commit_round))
        self._last_commit_round = round_
        t_prop, t_vote, t_qc = rec[_T_PROPOSED], rec[_T_VOTE], rec[_T_QC]
        entry = {"round": round_, "digest": digest[:8].hex()}
        if t_vote:
            self.hist["propose_to_vote"].observe(t_vote - t_prop)
            entry["propose_to_vote_ms"] = round((t_vote - t_prop) * 1e3, 3)
        if t_qc and t_vote:
            self.hist["vote_to_qc"].observe(t_qc - t_vote)
            entry["vote_to_qc_ms"] = round((t_qc - t_vote) * 1e3, 3)
        if t_qc:
            self.hist["qc_to_commit"].observe(now - t_qc)
            entry["qc_to_commit_ms"] = round((now - t_qc) * 1e3, 3)
        self.hist["propose_to_commit"].observe(now - t_prop)
        entry["propose_to_commit_ms"] = round((now - t_prop) * 1e3, 3)
        self.ring.append(entry)

    def mark_timeout(self) -> None:
        self.timeouts.inc()

    def mark_tc_advance(self) -> None:
        self.tcs.inc()

    # ---- snapshot (off the hot path) -----------------------------------

    def open_count(self) -> int:
        return len(self._open)

    def recent(self, n: int = 16) -> list[dict]:
        """The newest ``n`` completed per-round trace records."""
        if n >= len(self.ring):
            return list(self.ring)
        return list(self.ring)[-n:]

    def to_json(self) -> dict:
        return {
            "commits": self.commits.value,
            "timeouts": self.timeouts.value,
            "tc_advances": self.tcs.value,
            "last_commit_round": self._last_commit_round,
            "open_traces": len(self._open),
            "edges": {e: self.hist[e].to_json() for e in EDGES},
            "payload_wait": self.payload_wait.to_json(),
            "round_gap": self.round_gap.to_json(scale=1.0, unit="rounds"),
        }


__all__ = ["TraceRecorder", "EDGES", "DEFAULT_CAPACITY", "DEFAULT_RING"]
