"""Live fleet health plane (ISSUE 13): delta frames, online anomaly
detectors, incident records, and the bounded campaign recorder.

Everything observability built before this module is post-hoc: the
journal, the spans, the Perfetto reconstruction all tell you the
committee was sick after the run ends.  This module is the *live* half:

- :func:`flatten` + :class:`DeltaStream` / :class:`DeltaDecoder` — the
  ``/delta`` wire format.  A node flattens its snapshot document into
  dot-keyed scalars and serves **delta frames** against a short frame
  history, keyed by a monotonic sequence number, so a scraper pulls
  O(changed keys) per tick instead of the whole document.  A decoder
  that misses a frame (sequence gap, node restart) drops its state and
  re-pulls a full frame — resync is one extra round trip, never a
  wrong merge.
- **Online anomaly detectors** — pure functions over sliding windows of
  ``(t, value)`` samples.  No I/O, no clock reads, no hidden state:
  every input (including the EWMA baseline) is a parameter and every
  output is an :class:`Incident` (or updated state), so each detector
  is unit-testable with fixture windows.
- :class:`CampaignRecorder` — a bounded fixed-interval time-series ring
  of the key gauges, persisted beside the journal as
  ``<node>-campaign.json`` (*not* ``.jsonl``: the journal loader globs
  ``*.jsonl``).  Minutes-to-hours of samples in well under 1 MB, so an
  hour-long remote campaign stays analyzable without unbounded logs.
- :class:`HealthMonitor` — the per-node async loop: samples the node's
  own snapshot, runs the node-local detectors, journals
  ``health.<kind>`` open/close edges (taxonomy-registered, rendered as
  the Perfetto incidents track) and logs ``Health incident: {json}``
  lines that ``benchmark/logs.py`` folds into the ``+ HEALTH`` block.

Fleet-level detectors (straggler, state-root divergence, expected-leader
stall attribution) need cross-node visibility and run in the scraper
(``benchmark/watch.py``) over the same pure functions.

This module is a stdlib-only leaf — no imports from the rest of the
package — so ``benchmark/watch.py`` and the analysis plane can import
it without dragging in the node runtime.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from collections import deque
from dataclasses import dataclass

log = logging.getLogger(__name__)

#: dynamic journal-edge family for incidents (taxonomy.HEALTH_PREFIX
#: mirrors this; kept literal here so this file stays import-free)
HEALTH_EDGE_PREFIX = "health."

#: every incident kind a detector can emit (docs + rendering order)
HEALTH_KINDS: tuple = (
    "leader_stall",
    "view_storm",
    "commit_collapse",
    "straggler",
    "shed_storm",
    "root_divergence",
    "epoch_skew",
    "crit_regime_shift",
    "bandwidth_storm",
)

# ---- delta-frame wire format ----------------------------------------------

_SCALARS = (int, float, str, bool)


def flatten(doc, prefix: str = "", out: dict | None = None) -> dict:
    """Flatten a nested snapshot document into dot-keyed scalars.

    Lists are indexed (``a.0``, ``a.1``); None and non-scalar leaves
    are dropped — the delta stream diffs scalar maps only.
    """
    if out is None:
        out = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            flatten(v, f"{prefix}{k}.", out)
        return out
    if isinstance(doc, (list, tuple)):
        for i, v in enumerate(doc):
            flatten(v, f"{prefix}{i}.", out)
        return out
    if isinstance(doc, _SCALARS):
        out[prefix[:-1]] = doc
    return out


#: frames of history a node keeps for delta serving: a scraper at a
#: 1 s tick tolerates ~this many missed pulls before paying a full frame
DELTA_HISTORY = 8


class DeltaStream:
    """Server side of ``/delta``: diff the current flat state against a
    short history of served frames.

    ``frame(doc, since)`` returns a **full** frame
    ``{"seq": s, "full": {...}}`` when ``since`` is unknown (first pull,
    history fallen off, node restarted) and a **delta** frame
    ``{"seq": s, "base": since, "set": {...}, "del": [...]}``
    otherwise.  ``seq`` only advances when the state actually changed,
    so an idle fleet serves empty deltas.
    """

    def __init__(self, history: int = DELTA_HISTORY):
        self.seq = 0
        self._frames: deque = deque(maxlen=history)  # (seq, flat state)

    def frame(self, doc: dict, since: int = -1) -> dict:
        flat = flatten(doc)
        if not self._frames or self._frames[-1][1] != flat:
            self.seq += 1
            self._frames.append((self.seq, flat))
        latest_seq, latest = self._frames[-1]
        base = None
        if 0 <= since <= latest_seq:
            for s, f in self._frames:
                if s == since:
                    base = f
                    break
        if base is None:
            return {"seq": latest_seq, "full": latest}
        sentinel = object()
        changed = {
            k: v for k, v in latest.items() if base.get(k, sentinel) != v
        }
        removed = [k for k in base if k not in latest]
        return {
            "seq": latest_seq,
            "base": since,
            "set": changed,
            "del": removed,
        }


class DeltaDecoder:
    """Client side of ``/delta``: apply frames, detect sequence gaps.

    ``apply`` returns the up-to-date flat state, or ``None`` on a gap
    (the delta's base is not the state we hold) — the caller re-pulls
    with ``since=-1`` (``self.since`` already reset) and merges the full
    frame next tick.
    """

    def __init__(self):
        self.seq = -1
        self.state: dict = {}
        self.resyncs = 0

    @property
    def since(self) -> int:
        return self.seq

    def apply(self, frame: dict) -> dict | None:
        if "full" in frame:
            self.state = dict(frame["full"])
            self.seq = frame["seq"]
            return self.state
        if frame.get("base") != self.seq:
            self.seq = -1
            self.state = {}
            self.resyncs += 1
            return None
        self.state.update(frame.get("set", {}))
        for k in frame.get("del", ()):
            self.state.pop(k, None)
        self.seq = frame["seq"]
        return self.state


# ---- sliding windows -------------------------------------------------------


class Window:
    """Bounded sliding window of ``(t, value)`` samples (time-trimmed
    and capacity-capped)."""

    def __init__(self, span_s: float = 60.0, capacity: int = 256):
        self.span_s = span_s
        self._q: deque = deque(maxlen=capacity)

    def push(self, t: float, v: float) -> None:
        self._q.append((t, v))
        while self._q and t - self._q[0][0] > self.span_s:
            self._q.popleft()

    def samples(self) -> list:
        return list(self._q)

    def __len__(self) -> int:
        return len(self._q)


def rate(samples) -> float | None:
    """Mean rate of change across a counter-sample window, or ``None``
    when the window spans no time (fewer than two samples)."""
    if len(samples) < 2:
        return None
    (t0, v0), (t1, v1) = samples[0], samples[-1]
    if t1 <= t0:
        return None
    return (v1 - v0) / (t1 - t0)


# ---- incidents -------------------------------------------------------------


@dataclass(frozen=True)
class Incident:
    """One detector firing: what, where, how bad, and the measured
    value that tripped the threshold."""

    kind: str
    node: str
    severity: str
    detail: str
    value: float = 0.0

    def to_json(self) -> dict:
        return {
            "kind": self.kind,
            "node": self.node,
            "severity": self.severity,
            "detail": self.detail,
            "value": round(self.value, 3),
        }


# ---- online anomaly detectors (pure functions) -----------------------------


def leader_stall(
    progress, now: float, timeout_s: float, k: float = 3.0, node: str = ""
) -> Incident | None:
    """No proposal/commit progress for ``k × timeout``.

    ``progress``: ``(t, monotonic counter)`` samples — the expected
    leader's proposal count fleet-side, or commit progress node-side
    (a stalled leader stalls every replica's commit counter).  Requires
    the window to cover at least ``k × timeout_s`` of observation so a
    cold start never fires.
    """
    if not progress:
        return None
    horizon = k * timeout_s
    if now - progress[0][0] < horizon:
        return None
    last_advance_t, last_v = progress[0]
    for t, v in progress[1:]:
        if v > last_v:
            last_advance_t, last_v = t, v
    stalled_s = now - last_advance_t
    if stalled_s < horizon:
        return None
    return Incident(
        "leader_stall",
        node,
        "crit",
        f"no progress for {stalled_s:.1f}s "
        f"(threshold {horizon:.1f}s = {k:g}x{timeout_s:g}s timeout)",
        stalled_s,
    )


def view_change_storm(
    tc_samples,
    baseline_ewma: float | None,
    alpha: float = 0.3,
    factor: float = 4.0,
    min_rate: float = 0.5,
    node: str = "",
) -> tuple:
    """TC rate above the EWMA baseline: ``(incident | None, new ewma)``.

    ``tc_samples``: ``(t, tc_advances total)``.  The first observed rate
    seeds the baseline; the baseline only absorbs quiet ticks (a storm
    must not normalize itself).  ``min_rate`` floors the trigger so a
    single TC against a zero baseline does not page.
    """
    r = rate(tc_samples)
    if r is None:
        return None, baseline_ewma
    if baseline_ewma is None:
        return None, r
    if r >= min_rate and r > factor * baseline_ewma:
        inc = Incident(
            "view_storm",
            node,
            "warn",
            f"TC rate {r:.2f}/s vs baseline {baseline_ewma:.2f}/s "
            f"(x{factor:g} threshold)",
            r,
        )
        return inc, baseline_ewma
    return None, (1.0 - alpha) * baseline_ewma + alpha * r


def bandwidth_storm(
    egress_samples,
    baseline_ewma: float | None,
    alpha: float = 0.3,
    factor: float = 4.0,
    min_rate: float = 65536.0,
    node: str = "",
) -> tuple:
    """Wire egress rate above the EWMA baseline (ISSUE 19):
    ``(incident | None, new ewma)``.

    ``egress_samples``: ``(t, net_tx_bytes total)`` from the node's flow
    accountant.  Same EWMA discipline as :func:`view_change_storm`: the
    first observed rate seeds the baseline and only quiet ticks are
    absorbed, so a retransmit or equivocation storm cannot normalize
    itself.  ``min_rate`` (bytes/s) floors the trigger so a chatty-idle
    committee never pages on its own heartbeat traffic.
    """
    r = rate(egress_samples)
    if r is None:
        return None, baseline_ewma
    if baseline_ewma is None:
        return None, r
    if r >= min_rate and r > factor * baseline_ewma:
        inc = Incident(
            "bandwidth_storm",
            node,
            "warn",
            f"wire egress {r / 1e3:.0f} kB/s vs baseline "
            f"{baseline_ewma / 1e3:.0f} kB/s (x{factor:g} threshold)",
            r,
        )
        return inc, baseline_ewma
    return None, (1.0 - alpha) * baseline_ewma + alpha * r


def commit_collapse(
    commit_samples,
    collapse_ratio: float = 0.25,
    min_baseline_rate: float = 1.0,
    node: str = "",
) -> Incident | None:
    """Recent commit rate collapsed vs. the window's own earlier rate.

    ``commit_samples``: ``(t, commits total)``.  Splits the window at
    its time midpoint; fires when the recent-half rate drops below
    ``collapse_ratio`` x the earlier-half rate and the earlier half was
    genuinely committing (``min_baseline_rate``).
    """
    if len(commit_samples) < 4:
        return None
    t_mid = (commit_samples[0][0] + commit_samples[-1][0]) / 2.0
    early = [s for s in commit_samples if s[0] <= t_mid]
    late = [s for s in commit_samples if s[0] >= t_mid]
    r_early, r_late = rate(early), rate(late)
    if r_early is None or r_late is None or r_early < min_baseline_rate:
        return None
    if r_late <= collapse_ratio * r_early:
        return Incident(
            "commit_collapse",
            node,
            "crit",
            f"commit rate {r_late:.2f}/s, was {r_early:.2f}/s "
            f"(<= {collapse_ratio:g}x)",
            r_late,
        )
    return None


def straggler(
    rounds_by_node: dict,
    offsets: dict,
    now: float,
    lag_rounds: float = 16.0,
    max_age_s: float = 5.0,
) -> list:
    """Nodes whose round trails the fleet head.

    ``rounds_by_node``: node -> ``(sample time, round)``; ``offsets``:
    node -> estimated clock offset seconds (subtracted from the sample
    time before the freshness check, so a skewed-but-reporting node is
    not misread as silent — clock-offset awareness, not lag inflation).
    Only nodes with a sample fresher than ``max_age_s`` participate; a
    silent node is the STALE column's problem, not a straggler verdict.
    """
    fresh = {}
    for name, (t, round_) in rounds_by_node.items():
        if now - (t - offsets.get(name, 0.0)) <= max_age_s:
            fresh[name] = round_
    if len(fresh) < 2:
        return []
    head = max(fresh.values())
    out = []
    for name in sorted(fresh):
        lag = head - fresh[name]
        if lag >= lag_rounds:
            out.append(
                Incident(
                    "straggler",
                    name,
                    "warn",
                    f"round {fresh[name]:.0f} trails fleet head "
                    f"{head:.0f} by {lag:.0f} rounds",
                    lag,
                )
            )
    return out


def shed_storm(
    shed_samples,
    rate_threshold: float = 20.0,
    min_shed: int = 10,
    node: str = "",
) -> Incident | None:
    """Ingest BUSY spike: the admission plane shedding faster than
    ``rate_threshold`` payloads/s across the window (and at least
    ``min_shed`` absolute, so one burst at window edge cannot fire)."""
    r = rate(shed_samples)
    if r is None:
        return None
    total = shed_samples[-1][1] - shed_samples[0][1]
    if total >= min_shed and r >= rate_threshold:
        return Incident(
            "shed_storm",
            node,
            "warn",
            f"ingest shedding {r:.1f} payloads/s "
            f"({total:.0f} over the window)",
            r,
        )
    return None


def root_divergence(roots_by_node: dict) -> list:
    """State-root mismatch at the same applied version — the PR 11
    state-root agreement invariant, caught live instead of at run end.

    ``roots_by_node``: node -> ``(version, root)``.  Fires one
    fleet-wide incident per divergent version, naming every root and
    its holders.
    """
    by_version: dict = {}
    for name, (version, root) in sorted(roots_by_node.items()):
        by_version.setdefault(version, {}).setdefault(root, []).append(name)
    out = []
    for version in sorted(by_version):
        holders = by_version[version]
        if len(holders) > 1:
            detail = "; ".join(
                f"{root[:16]}..@{','.join(nodes)}"
                for root, nodes in sorted(holders.items())
            )
            out.append(
                Incident(
                    "root_divergence",
                    "",
                    "crit",
                    f"state roots diverge at version {version}: {detail}",
                    float(version),
                )
            )
    return out


def crit_regime_shift(
    regime_samples, confirm: int = 3, node: str = ""
) -> Incident | None:
    """The node's rolling commit critical-path regime changed and STUCK.

    ``regime_samples``: oldest-to-newest regime strings (one per health
    tick with enough commit samples; ticks without an attribution are
    simply not pushed).  Fires when the newest ``confirm`` consecutive
    samples agree on a regime different from the one established before
    them — a one-tick flap (a single slow round misclassified) never
    pages, but "this committee went from verify-bound to network-bound
    and stayed there" does.  Pure function: unit-testable with fixture
    windows like every other detector here.
    """
    seq = [r for r in regime_samples if r and r != "unknown"]
    if len(seq) < confirm + 1:
        return None
    head = seq[-confirm:]
    new = head[0]
    if any(r != new for r in head):
        return None  # the shift has not settled yet
    prev = None
    for r in reversed(seq[:-confirm]):
        if r != new:
            prev = r
            break
    if prev is None:
        return None
    return Incident(
        "crit_regime_shift",
        node,
        "warn",
        f"commit critical path shifted {prev} -> {new} "
        f"(confirmed over {confirm} ticks)",
        float(confirm),
    )


def epoch_skew(epochs_by_node: dict) -> list:
    """Committee-epoch disagreement across the live fleet (ISSUE 14):
    every node's ``core_epoch`` gauge should match once a
    reconfiguration boundary has passed — a node stuck on an older
    epoch missed (or refused) a certified schedule splice and will stop
    verifying new-epoch certificates.

    ``epochs_by_node``: node -> reported active epoch.  Fires one
    fleet-wide crit incident naming the head epoch and every laggard.
    A skew is legitimate only for the instants nodes cross the boundary
    a round apart, so callers tolerate one-tick flaps; a *persisting*
    incident is the real signal.
    """
    fresh = {
        name: int(e) for name, e in epochs_by_node.items() if e is not None
    }
    if len(fresh) < 2:
        return []
    head = max(fresh.values())
    laggards = {n: e for n, e in sorted(fresh.items()) if e < head}
    if not laggards:
        return []
    detail = ", ".join(f"{n}@{e}" for n, e in laggards.items())
    return [
        Incident(
            "epoch_skew",
            "",
            "crit",
            f"fleet head epoch {head}, trailing: {detail}",
            float(head),
        )
    ]


# ---- campaign recorder -----------------------------------------------------

CAMPAIGN_SUFFIX = "-campaign.json"


class CampaignRecorder:
    """Bounded fixed-interval time-series ring of the key gauges.

    ``sample`` is rate-gated to ``interval_s`` and the ring is
    capacity-capped, so hours of campaign keep a fixed footprint: at
    the default 4096 samples x ~10 short keys the persisted JSON stays
    well under 1 MB.  ``persist`` rewrites atomically (tmp + rename)
    beside the journal as ``<node>-campaign.json`` — a name the journal
    loader's ``*.jsonl`` glob never matches.
    """

    def __init__(
        self,
        node: str,
        path: str | None = None,
        interval_s: float = 1.0,
        capacity: int = 4096,
    ):
        self.node = node
        self.path = path
        self.interval_s = interval_s
        self._samples: deque = deque(maxlen=capacity)
        self._last_t: float | None = None

    def sample(self, t: float, values: dict) -> bool:
        """Record one row when the interval gate opens; returns whether
        the row was taken."""
        if self._last_t is not None and t - self._last_t < self.interval_s:
            return False
        self._last_t = t
        self._samples.append({"t": round(t, 3), **values})
        return True

    def __len__(self) -> int:
        return len(self._samples)

    def to_json(self) -> dict:
        return {
            "node": self.node,
            "interval_s": self.interval_s,
            "samples": list(self._samples),
        }

    def persist(self) -> str | None:
        if self.path is None:
            return None
        tmp = f"{self.path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, sort_keys=True)
        os.replace(tmp, self.path)
        return self.path

    @staticmethod
    def load(path: str) -> dict:
        with open(path, encoding="utf-8") as f:
            return json.load(f)


# ---- per-node monitor ------------------------------------------------------

#: ticks between campaign persists (interval-relative, ~every 30 s at
#: the default 1 s tick)
PERSIST_EVERY = 30

#: quiet ticks before an open incident is closed (hysteresis: a
#: detector flapping at threshold must not spray open/close edges)
CLEAR_AFTER = 2


class HealthMonitor:
    """The per-node online health loop.

    Samples the node's own telemetry snapshot once per ``interval_s``,
    feeds the node-local detectors (leader-stall via commit progress,
    view-change storm, commit collapse, shed storm, bandwidth storm),
    and turns firings
    into incident records on three surfaces at once: a
    ``health.<kind>`` open/close journal edge pair (the Perfetto
    incidents track), a ``Health incident: {json}`` log line (the
    ``+ HEALTH`` SUMMARY block), and the campaign ring.
    """

    def __init__(
        self,
        tel,
        node: str,
        timeout_s: float,
        interval_s: float = 1.0,
        stall_k: float = 3.0,
        campaign_path: str | None = None,
        logger=None,
        attribution_fn=None,
    ):
        self._tel = tel
        self.node = node
        self.timeout_s = max(timeout_s, 0.1)
        self.interval_s = interval_s
        self.stall_k = stall_k
        self._log = logger or log
        span = max(60.0, 4 * stall_k * self.timeout_s)
        self._w_commits = Window(span_s=span)
        self._w_tcs = Window(span_s=span)
        self._w_shed = Window(span_s=span)
        self._w_net = Window(span_s=span)
        self._tc_ewma: float | None = None
        self._net_ewma: float | None = None
        # rolling commit critical-path attribution: ``attribution_fn``
        # (wired by the node from telemetry.critpath.rolling_attribution
        # over the trace ring — this module stays import-free) returns
        # {"dominant", "regime", ...} or None when under-sampled
        self._attribution_fn = attribution_fn
        self._regimes: deque = deque(maxlen=32)
        self.last_attribution: dict | None = None
        self._open: dict = {}  # kind -> Incident
        self._quiet: dict = {}  # kind -> consecutive quiet ticks
        self.recorder = CampaignRecorder(
            node, campaign_path, interval_s=max(interval_s, 1.0)
        )
        self._ticks = 0

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                await asyncio.sleep(self.interval_s)
                try:
                    self.tick(loop.time())
                except Exception as e:  # noqa: BLE001 — never kill the node
                    self._log.warning("health tick failed: %s", e)
        finally:
            self.close()

    # -- one sampling tick (sync, also driven directly by tests) ---------

    def tick(self, now: float) -> list:
        snap = self._tel.snapshot()
        trace = snap.get("trace", {}) or {}
        ingest = snap.get("ingest", {}) or {}
        state = snap.get("state", {}) or {}
        flows = snap.get("flows", {}) or {}
        commits = float(trace.get("commits", 0) or 0)
        tcs = float(trace.get("tc_advances", 0) or 0)
        shed = float(ingest.get("shed_total", 0) or 0)
        net_tx = float(flows.get("tx_bytes", 0) or 0)
        round_ = int(trace.get("last_commit_round", 0) or 0)
        self._w_commits.push(now, commits)
        self._w_tcs.push(now, tcs)
        self._w_shed.push(now, shed)
        if flows.get("enabled"):
            self._w_net.push(now, net_tx)

        fired = []
        inc = leader_stall(
            self._w_commits.samples(),
            now,
            self.timeout_s,
            k=self.stall_k,
            node=self.node,
        )
        if inc:
            fired.append(inc)
        inc, self._tc_ewma = view_change_storm(
            self._w_tcs.samples(), self._tc_ewma, node=self.node
        )
        if inc:
            fired.append(inc)
        inc = commit_collapse(self._w_commits.samples(), node=self.node)
        if inc:
            fired.append(inc)
        inc = shed_storm(self._w_shed.samples(), node=self.node)
        if inc:
            fired.append(inc)
        inc, self._net_ewma = bandwidth_storm(
            self._w_net.samples(), self._net_ewma, node=self.node
        )
        if inc:
            fired.append(inc)
        if self._attribution_fn is not None:
            try:
                att = self._attribution_fn()
            except Exception:  # noqa: BLE001 — attribution is advisory
                att = None
            if att:
                self.last_attribution = att
                regime = att.get("regime")
                if regime:
                    self._regimes.append(regime)
                inc = crit_regime_shift(
                    list(self._regimes), node=self.node
                )
                if inc:
                    fired.append(inc)

        self._transition(fired, round_)

        if self.recorder.sample(
            now,
            {
                "round": round_,
                "commits": commits,
                "tcs": tcs,
                "shed": shed,
                "net_tx": net_tx,
                "credit": ingest.get("last_credit", 0),
                "version": state.get("version", 0),
                "incidents": len(self._open),
            },
        ):
            self._ticks += 1
            if self._ticks % PERSIST_EVERY == 0:
                self.recorder.persist()
        return fired

    def _transition(self, fired: list, round_: int) -> None:
        """Open/close incident edges with clear-side hysteresis."""
        now_kinds = {i.kind: i for i in fired}
        for kind, inc in now_kinds.items():
            self._quiet[kind] = 0
            if kind not in self._open:
                self._open[kind] = inc
                self._emit(inc, "open", round_)
        for kind in list(self._open):
            if kind in now_kinds:
                continue
            self._quiet[kind] = self._quiet.get(kind, 0) + 1
            if self._quiet[kind] >= CLEAR_AFTER:
                inc = self._open.pop(kind)
                self._quiet.pop(kind, None)
                self._emit(inc, "close", round_)

    def _emit(self, inc: Incident, phase: str, round_: int) -> None:
        doc = {**inc.to_json(), "phase": phase}
        self._log.info("Health incident: %s", json.dumps(doc, sort_keys=True))
        journal = getattr(self._tel, "journal", None)
        if journal is not None:
            journal.record(f"health.{inc.kind}", round_=round_, peer=phase)

    def open_incidents(self) -> list:
        return list(self._open.values())

    def close(self) -> None:
        """Final campaign persist (node shutdown)."""
        try:
            self.recorder.persist()
        except OSError as e:
            self._log.warning("campaign persist failed: %s", e)


__all__ = [
    "HEALTH_EDGE_PREFIX",
    "HEALTH_KINDS",
    "CAMPAIGN_SUFFIX",
    "DELTA_HISTORY",
    "flatten",
    "DeltaStream",
    "DeltaDecoder",
    "Window",
    "rate",
    "Incident",
    "leader_stall",
    "view_change_storm",
    "bandwidth_storm",
    "commit_collapse",
    "straggler",
    "shed_storm",
    "root_divergence",
    "epoch_skew",
    "crit_regime_shift",
    "CampaignRecorder",
    "HealthMonitor",
]
