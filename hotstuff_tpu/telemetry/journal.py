"""Flight recorder: a bounded, allocation-light consensus event journal.

Every node appends its consensus lifecycle events — propose / receive /
vote-sent / vote-received / QC-formed / commit, timeout / TC, sync
request / reply, and the network send/recv edges those imply — to a
per-node :class:`Journal`.  Each record carries (event, round, block
digest, peer, monotonic ns, wall ns) and is persisted as JSONL ring
segments under the node's store path (or ``--journal-dir`` /
``HOTSTUFF_JOURNAL_DIR``).  ``benchmark/traces.py`` merges the per-node
journals of a run, estimates per-node clock offsets from the matched
send/recv pairs, and reconstructs the committee-wide timeline of every
committed (and timed-out) round.

Design constraints (ISSUE 2 tentpole):

- **Hot path is append-only**: ``record()`` is two clock reads, one
  tuple, one list append, and a length check.  JSON formatting and file
  I/O happen at flush time only (buffer threshold, force-flush points,
  or close) — never per event.
- **Bounded on disk**: segments rotate at ``segment_bytes`` and the ring
  keeps the newest ``segments`` files; a run that outlives the ring
  loses its OLDEST events (a flight recorder, not an archive).
- **Crash durable**: the core force-flushes on timeout and view-change
  (the interesting failures), and module-level atexit + SIGTERM/SIGINT
  hooks flush every live journal on the way down — a bench harness
  killing the committee with SIGTERM still yields complete journals.
- **Off by default**: with journaling off no Journal is constructed and
  every emission site is a single ``if journal is not None`` — the
  telemetry overhead contract (docs/TELEMETRY.md) is unchanged.

Record wire format (one JSON object per line)::

    {"e":"commit","r":12,"d":"wT2Fq1p...","p":"","m":123456789,"w":1699...,"s":41}

``e`` event name, ``r`` round (0 = n/a), ``d`` block digest (16-char
base64 prefix, the same display the node logs use; "" = n/a), ``p``
peer (8-char node id, "" = n/a / broadcast), ``m`` monotonic ns, ``w``
wall-clock ns, ``s`` per-node record sequence number (monotonic across
segments and — with ``resume=True`` — across restarts; the merge in
``benchmark/traces.py`` dedups replayed records by (node, s)).  Each
segment opens with a ``{"e":"meta",...}`` line naming the node
(filenames are sanitized and must not be trusted) and carrying the
cumulative ``tot``/``drop`` record counters, so trace-time consumers
can report journal coverage instead of silently attributing from a
truncated ring.

Timestamps route through the ambient clock seam
(``hotstuff_tpu/utils/clock.py``): production reads real time, the
deterministic simulator's VirtualClock makes journal content — and
therefore critical-path attribution — reproducible per seed.
"""

from __future__ import annotations

import atexit
import json
import logging
import os
import re
import signal
import threading
import time

from ..utils.clock import SYSTEM_CLOCK, default_clock

log = logging.getLogger(__name__)

SEGMENT_BYTES = 4 << 20  # rotate segments at ~4 MiB
SEGMENTS = 8  # ring depth: <= ~32 MiB per node on disk
BUFFER_RECORDS = 256  # hot-path buffer length before an opportunistic flush

# ---- crash-flush hooks (module level, one set per process) --------------

_JOURNALS: list["Journal"] = []
_HOOKS_INSTALLED = False
_PREV_HANDLERS: dict[int, object] = {}


def flush_all() -> None:
    """Flush every live journal in this process (atexit/signal path —
    must never raise)."""
    for j in list(_JOURNALS):
        try:
            j.flush()
        except Exception:  # noqa: BLE001 — a crash hook must not crash
            pass


def _signal_flush(signum, frame) -> None:
    flush_all()
    prev = _PREV_HANDLERS.get(signum)
    if callable(prev):
        prev(signum, frame)
    else:
        # restore the default disposition and re-deliver so the process
        # dies with the correct signal exit status (the bench harness
        # SIGTERMs the committee and checks nothing hung)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def _install_crash_hooks() -> None:
    global _HOOKS_INSTALLED
    if _HOOKS_INSTALLED:
        return
    _HOOKS_INSTALLED = True
    atexit.register(flush_all)
    # signal handlers only from the main thread (signal module contract);
    # elsewhere the atexit hook still covers orderly exits
    if threading.current_thread() is not threading.main_thread():
        return
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            prev = signal.getsignal(sig)
            if prev is _signal_flush:
                continue
            _PREV_HANDLERS[sig] = prev
            signal.signal(sig, _signal_flush)
        except (ValueError, OSError):  # non-main thread race / exotic env
            pass


def _sanitize(name: str) -> str:
    """Filename-safe node id (node ids are base64 prefixes and may
    contain '/' or '+'); the authoritative id lives in the meta line."""
    return "".join(c if c.isalnum() else "_" for c in name) or "node"


class Journal:
    """One node's bounded JSONL ring-segment event journal."""

    __slots__ = (
        "node",
        "dir",
        "segment_bytes",
        "segments",
        "buffer_records",
        "records_total",
        "segments_rotated",
        "dropped_records_total",
        "_prefix",
        "_buf",
        "_file",
        "_bytes",
        "_seq",
        "_rec_seq",
        "_paths",
        "_path_records",
        "_closed",
        "_mono_ns",
        "_wall_ns",
    )

    def __init__(
        self,
        node: str,
        dir_path: str,
        *,
        segment_bytes: int = SEGMENT_BYTES,
        segments: int = SEGMENTS,
        buffer_records: int = BUFFER_RECORDS,
        resume: bool = False,
    ):
        self.node = str(node)
        self.dir = dir_path
        self.segment_bytes = max(1, int(segment_bytes))
        self.segments = max(1, int(segments))
        self.buffer_records = max(1, int(buffer_records))
        self.records_total = 0
        self.segments_rotated = 0
        self.dropped_records_total = 0
        self._prefix = _sanitize(self.node)
        self._buf: list[tuple] = []
        self._file = None
        self._bytes = 0
        self._seq = 0
        self._rec_seq = 0
        self._paths: list[str] = []
        self._path_records: list[int] = []
        self._closed = False
        # Bind the ambient clock once at boot: real time in production,
        # the simulator's VirtualClock when run_schedule swapped the seam
        # before spawning the committee (deterministic journal content).
        clk = default_clock()
        self._mono_ns = clk.monotonic_ns
        wall_ns = getattr(clk, "time_ns", None)
        if wall_ns is None:
            if clk is SYSTEM_CLOCK:
                wall_ns = time.time_ns
            else:
                wall_ns = lambda c=clk: int(c.time() * 1e9)  # noqa: E731
        self._wall_ns = wall_ns
        os.makedirs(self.dir, exist_ok=True)
        if resume:
            # crash-restart: keep the previous boot's segments and keep
            # numbering (segments AND record seqs) after them, so the
            # merge sees one continuous, dedupable per-node stream
            self._resume_scan()
        else:
            # a previous run's segments under the same prefix would
            # merge into this run's timeline at trace time — drop them
            for fname in os.listdir(self.dir):
                if fname.startswith(self._prefix + "-") and fname.endswith(
                    ".jsonl"
                ):
                    try:
                        os.unlink(os.path.join(self.dir, fname))
                    except OSError:
                        pass
        _JOURNALS.append(self)
        _install_crash_hooks()

    def _resume_scan(self) -> None:
        """Adopt pre-existing ring segments (crash-restart): re-enter
        them into the ring accounting and continue the segment / record
        sequence numbering after the highest persisted values.  A torn
        tail line may hide the true max seq — restart records then reuse
        seq values, which the (node, seq) merge dedup resolves by
        keeping the first occurrence."""
        seg_re = re.compile(
            re.escape(self._prefix) + r"-(\d{6})\.jsonl$"
        )
        found: list[tuple[int, str]] = []
        for fname in os.listdir(self.dir):
            m = seg_re.match(fname)
            if m:
                found.append((int(m.group(1)), os.path.join(self.dir, fname)))
        found.sort()
        max_s = -1
        for seg_no, path in found:
            nrec = 0
            try:
                with open(path) as f:
                    for line in f:
                        try:
                            rec = json.loads(line)
                        except ValueError:  # torn tail line
                            continue
                        if rec.get("e") == "meta":
                            self.dropped_records_total = max(
                                self.dropped_records_total,
                                int(rec.get("drop", 0)),
                            )
                            continue
                        nrec += 1
                        s = rec.get("s")
                        if isinstance(s, int) and s > max_s:
                            max_s = s
            except OSError:
                continue
            self._paths.append(path)
            self._path_records.append(nrec)
            self.records_total += nrec
            self._seq = max(self._seq, seg_no + 1)
        self._rec_seq = max_s + 1

    # ---- hot path --------------------------------------------------------

    def record(
        self,
        event: str,
        round_: int = 0,
        digest=None,
        peer: str = "",
        dur_ns: int | None = None,
    ) -> None:
        """Append one event.  ``digest`` is a crypto value object (or
        None); its base64 rendering is deferred to flush time.
        ``dur_ns`` (optional) marks a DURATION record — a span ending at
        this record's timestamps (the verify-pipeline profiler's
        ``span`` events); it lands in the wire format as ``"u"``."""
        s = self._rec_seq
        self._rec_seq = s + 1
        buf = self._buf
        buf.append(
            (
                event,
                round_,
                digest,
                peer,
                self._mono_ns(),
                self._wall_ns(),
                s,
                dur_ns,
            )
        )
        if len(buf) >= self.buffer_records:
            self.flush()

    # ---- flush / rotation ------------------------------------------------

    def flush(self) -> None:
        """Format and persist the buffered records (force-flush points:
        local timeout, TC advance, shutdown, crash hooks)."""
        buf = self._buf
        if not buf or self._closed:
            return
        self._buf = []
        parts = []
        for e, r, d, p, m, w, s, u in buf:
            ds = d.encode_base64()[:16] if d is not None else ""
            tail = f',"u":{u}' if u is not None else ""
            parts.append(
                f'{{"e":"{e}","r":{r},"d":"{ds}","p":"{p}","m":{m},"w":{w},'
                f'"s":{s}{tail}}}\n'
            )
        data = "".join(parts)
        try:
            f = self._file
            if f is None:
                f = self._open_segment()
            f.write(data)
            f.flush()
        except OSError as exc:
            log.warning("journal flush failed for %s: %s", self.node, exc)
            return
        self._bytes += len(data)
        self.records_total += len(buf)
        if self._path_records:
            self._path_records[-1] += len(buf)
        if self._bytes >= self.segment_bytes:
            self._rotate()

    def _open_segment(self):
        # enforce the ring bound before adding a segment (rotation also
        # trims, but a resumed ring can already be at capacity here)
        while len(self._paths) >= self.segments:
            self._drop_oldest()
        path = os.path.join(
            self.dir, f"{self._prefix}-{self._seq:06d}.jsonl"
        )
        f = open(path, "w")
        self._file = f
        self._bytes = 0
        self._paths.append(path)
        self._path_records.append(0)
        meta = (
            f'{{"e":"meta","n":"{self.node}","seg":{self._seq},'
            f'"pid":{os.getpid()},"m":{self._mono_ns()},'
            f'"w":{self._wall_ns()},"tot":{self.records_total},'
            f'"drop":{self.dropped_records_total}}}\n'
        )
        f.write(meta)
        self._bytes += len(meta)
        return f

    def _drop_oldest(self) -> None:
        """Unlink the oldest ring segment, counting its records as
        dropped — the no-silent-caps counter behind ``journal coverage``
        in the + CRITPATH block and ``journal.dropped`` in /delta."""
        oldest = self._paths.pop(0)
        self.dropped_records_total += self._path_records.pop(0)
        try:
            os.unlink(oldest)
        except OSError:
            pass

    def _rotate(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
        self._file = None
        self._seq += 1
        self.segments_rotated += 1
        while len(self._paths) >= self.segments:
            self._drop_oldest()

    # ---- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        self._closed = True
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        try:
            _JOURNALS.remove(self)
        except ValueError:
            pass

    def stats(self) -> dict:
        """Snapshot-document section (telemetry pull model)."""
        return {
            "records": self.records_total,
            "buffered": len(self._buf),
            "segments": len(self._paths),
            "rotated": self.segments_rotated,
            "dropped": self.dropped_records_total,
            "dir": self.dir,
        }


__all__ = [
    "Journal",
    "flush_all",
    "SEGMENT_BYTES",
    "SEGMENTS",
    "BUFFER_RECORDS",
]
