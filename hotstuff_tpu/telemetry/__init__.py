"""hotstuff_tpu.telemetry — the permanent attribution layer.

Three pieces (ISSUE 1 tentpole):

1. **Per-round trace recorder** (``trace.py``): timestamps each block's
   lifecycle edges (proposed -> first-vote -> QC-formed -> committed,
   plus view-change/timeout edges) into a bounded ring buffer with
   fixed log-bucket latency histograms.
2. **Component gauges/counters** (``metrics.py`` instruments): the
   crypto verify services, the network senders/pools, and the store
   self-register into one process-wide :class:`Registry`, labelled per
   node (co-located committees share the process).
3. **Export** (``exporter.py``): an optional stdlib-only HTTP
   ``/metrics`` endpoint (Prometheus text format, off by default) plus
   a periodic ``Telemetry snapshot: {json}`` log line whose document is
   a superset of the ``Work stats:`` one (the scaling harness's scrape
   contract is subsumed, not broken).

Enablement: ``HOTSTUFF_TELEMETRY=1``, or setting a metrics port
(``--metrics-port`` / ``HOTSTUFF_METRICS_PORT`` — a scrape endpoint
implies collection), or :func:`enable` from code.  Disabled (the
default), ``for_node`` returns ``None`` and every consensus hook is a
single ``if tel is not None`` — no per-message allocation, no writes.

Overhead budget when enabled: each lifecycle mark is a dict lookup plus
scalar stores; each histogram observe is a bisect over a static bound
tuple plus three scalar updates; gauges are pull-model (evaluated at
scrape/snapshot time only).  Nothing on the hot path allocates
per-message; per-*proposal* records (one small list each) are the only
steady-state allocation and both record maps are bounded.
"""

from __future__ import annotations

import os
from typing import Callable

from .metrics import (
    LATENCY_BOUNDS_S,
    SIZE_BOUNDS,
    Counter,
    FloatCounter,
    Gauge,
    Histogram,
    Registry,
)
from .trace import EDGES, TraceRecorder
from . import spans

_REGISTRY = Registry()
_NODES: dict[str, "NodeTelemetry"] = {}
_FORCED = False
_JOURNAL_DIR: str | None = None  # forced via --journal-dir

#: per-PEER network gauges (``net_peer_*``) are registered for at most
#: this many peers per sender role — label cardinality stays bounded at
#: any committee size.  Peers beyond the cap are NEVER silently dropped
#: (ISSUE 19 no-silent-caps rule): a ``net_peers_elided`` gauge counts
#: them, the snapshot's ``net.peer`` block ranks ALL peers by flow
#: bytes and shows the top-K, and byte totals always cover everyone.
PEER_GAUGE_MAX_COMMITTEE = 8


def registry() -> Registry:
    """The process-wide instrument registry (what /metrics renders)."""
    return _REGISTRY


def enable() -> None:
    """Force-enable telemetry for this process (the CLI calls this when
    a metrics port is configured)."""
    global _FORCED
    _FORCED = True


def enabled() -> bool:
    if _FORCED:
        return True
    if journal_enabled():
        # the flight recorder rides on the NodeTelemetry handle, so
        # journaling implies collection
        return True
    if spans.enabled():
        # the span profiler feeds verify_stage_ms histograms, so
        # profiling implies collection too
        return True
    env = os.environ.get("HOTSTUFF_TELEMETRY")
    if env is not None:
        return env.strip().lower() not in ("", "0", "false", "no", "off")
    return bool(os.environ.get("HOTSTUFF_METRICS_PORT"))


def set_journal_dir(path: str | None) -> None:
    """Force-enable journaling into ``path`` (the CLI's --journal-dir)."""
    global _JOURNAL_DIR
    _JOURNAL_DIR = path


def journal_enabled() -> bool:
    """Is the flight recorder (telemetry/journal.py) on?  Off by
    default: ``HOTSTUFF_JOURNAL=1``, ``HOTSTUFF_JOURNAL_DIR=<dir>``, or
    ``--journal-dir`` enable it."""
    if _JOURNAL_DIR is not None:
        return True
    env = os.environ.get("HOTSTUFF_JOURNAL")
    if env is not None and env.strip().lower() not in (
        "", "0", "false", "no", "off",
    ):
        return True
    return bool(os.environ.get("HOTSTUFF_JOURNAL_DIR"))


def journal_dir(store_path: str) -> str | None:
    """The journal directory for a node at ``store_path``, or None when
    journaling is off.  Resolution: --journal-dir, then
    HOTSTUFF_JOURNAL_DIR, then ``<store_path>.journal`` (the "under the
    node's store path" default)."""
    if not journal_enabled():
        return None
    if _JOURNAL_DIR is not None:
        return _JOURNAL_DIR
    env = os.environ.get("HOTSTUFF_JOURNAL_DIR", "").strip()
    if env:
        return env
    return f"{store_path}.journal"


def for_node(name) -> "NodeTelemetry | None":
    """The node's telemetry handle, or None when telemetry is off —
    callers guard every hook with ``if tel is not None``."""
    if not enabled():
        return None
    key = str(name)
    tel = _NODES.get(key)
    if tel is None:
        tel = _NODES[key] = NodeTelemetry(key)
    return tel


def snapshot_all() -> dict:
    """One snapshot document per node in this process (/snapshot)."""
    return {n: t.snapshot() for n, t in _NODES.items()}


def health_enabled() -> bool:
    """Is the per-node health monitor (telemetry/health.py) on?  Off by
    default: ``HOTSTUFF_HEALTH=1`` / ``--health`` enable it."""
    env = os.environ.get("HOTSTUFF_HEALTH")
    return env is not None and env.strip().lower() not in (
        "", "0", "false", "no", "off",
    )


def export_doc() -> dict:
    """The health-plane export document (``/delta``): every node's
    snapshot sections (state-root cursor, ingest, trace) plus every
    node-labelled registry instrument under a ``metrics`` block — the
    nested doc the DeltaStream flattens into delta frames."""
    doc = snapshot_all()
    for inst in _REGISTRY:
        labels = getattr(inst, "labels", None) or {}
        node = labels.get("node")
        if node is None or node not in doc:
            continue
        key = inst.name
        extra = sorted(
            (k, v) for k, v in labels.items() if k != "node"
        )
        if extra:
            key += "{" + ",".join(f"{k}={v}" for k, v in extra) + "}"
        doc[node].setdefault("metrics", {})[key] = inst.to_json()
    return doc


def trace_all(n: int = 32) -> dict:
    """The newest completed per-round trace records per node (/trace)."""
    return {name: t.trace.recent(n) for name, t in _NODES.items()}


def reset() -> None:
    """Drop all registered instruments and node handles (tests only)."""
    global _REGISTRY, _FORCED, _JOURNAL_DIR
    _REGISTRY = Registry()
    _NODES.clear()
    _FORCED = False
    _JOURNAL_DIR = None
    spans.disable()


async def maybe_start_server(port: int | None, host: str = "0.0.0.0"):
    """Start the /metrics endpoint when ``port`` is configured (0 =
    ephemeral, logged at startup); returns the server or None."""
    if port is None:
        return None
    from .exporter import MetricsServer

    enable()
    return await MetricsServer(_REGISTRY, host=host, port=port).start()


class NodeTelemetry:
    """Per-node facade over the shared registry: the trace recorder,
    node-labelled instrument constructors, and the snapshot document.

    Components contribute to the snapshot either through instruments
    (labelled with this node) or through ``add_section(name, fn)`` —
    ``fn`` is evaluated at snapshot time (pull model)."""

    def __init__(self, node: str, registry: Registry | None = None):
        self.node = str(node)
        self.registry = registry if registry is not None else _REGISTRY
        self.labels = {"node": self.node}
        self.trace = TraceRecorder(self.registry, self.labels)
        self.workstats = None  # utils.workstats.WorkStats, attached by Node
        self.journal = None  # telemetry.journal.Journal, attached by Node
        self.flows = None  # telemetry.flows.FlowAccounting, attached by Node
        self._sections: dict[str, Callable[[], dict]] = {}
        self._senders: list[tuple[str, object]] = []
        # peer short-name -> [(sender, address)]: feeds the per-peer
        # snapshot block at small committee sizes (register_network)
        self._peer_conns: dict[str, list[tuple[object, object]]] = {}

    # ---- instrument constructors (node-labelled) -----------------------

    def counter(self, name: str, help_: str = "") -> Counter:
        return self.registry.counter(name, help_, dict(self.labels))

    def float_counter(self, name: str, help_: str = "") -> FloatCounter:
        return self.registry.float_counter(name, help_, dict(self.labels))

    def gauge(self, name: str, help_: str = "", fn=None) -> Gauge:
        return self.registry.gauge(name, help_, dict(self.labels), fn=fn)

    def histogram(
        self, name: str, help_: str = "", bounds=LATENCY_BOUNDS_S
    ) -> Histogram:
        return self.registry.histogram(
            name, help_, dict(self.labels), bounds=bounds
        )

    # ---- component registration ----------------------------------------

    def attach_workstats(self, stats) -> None:
        self.workstats = stats

    def attach_journal(self, journal) -> None:
        """Attach the node's flight recorder (telemetry/journal.py);
        consensus actors pick it up as ``telemetry.journal`` at boot."""
        self.journal = journal
        self.add_section("journal", journal.stats)

    def attach_flows(self, flows) -> None:
        """Attach the node's wire-level flow accountant
        (telemetry/flows.py): snapshot section, /metrics byte gauges,
        and the sampled ``net.tx``/``net.rx`` journal records."""
        self.flows = flows
        flows.bind_journal(lambda: self.journal)
        self.add_section("flows", flows.snapshot)
        if not flows.enabled:
            return
        self.gauge(
            "net_tx_bytes",
            "Wire bytes written across all links (frames + prefixes)",
            fn=flows.tx_bytes,
        )
        self.gauge(
            "net_rx_bytes",
            "Wire bytes read across all links (frames + prefixes)",
            fn=flows.rx_bytes,
        )
        self.gauge(
            "net_retx_bytes",
            "Wire bytes retransmitted by reliable links (subset of tx)",
            fn=flows.retx_bytes,
        )

    def add_section(self, name: str, fn: Callable[[], dict]) -> None:
        self._sections[name] = fn

    def register_store(self, store) -> None:
        engine = getattr(store, "engine", None)
        if engine is not None and hasattr(engine, "__len__"):
            self.gauge(
                "store_keys",
                "Live keys in the node's store engine",
                fn=lambda e=engine: len(e),
            )

    def register_network(self, role: str, sender, peers=None) -> None:
        """Wire pull gauges over a sender's pool: occupancy, idle-LRU
        evictions, per-peer retry/backoff state, pacing stalls.  Counts
        from evicted connections age out with them (live-peer view).

        ``peers``: optional [(public key, address)] of this sender's
        live peers (wired by Consensus.spawn at EVERY committee size) —
        per-PEER gauges are exported under ``net_peer_*`` in /metrics
        for the first PEER_GAUGE_MAX_COMMITTEE peers, the rest counted
        by ``net_peers_elided`` (never silently dropped), and a ranked
        ``net.peer`` block appears in the snapshot."""
        self._senders.append((role, sender))
        labels = {**self.labels, "role": role}
        reg = self.registry

        def conns(s=sender):
            return getattr(s, "_connections", {}).values()

        reg.gauge(
            "net_pool_connections",
            "Live connections in the sender's pool",
            labels,
            fn=lambda: len(conns()),
        )
        reg.gauge(
            "net_pool_evictions",
            "Idle connections LRU-evicted by the pool bound",
            labels,
            fn=lambda s=sender: getattr(s, "pool_evictions", 0),
        )
        reg.gauge(
            "net_peers_retrying",
            "Live peers currently disconnected (connect-retry/backoff)",
            labels,
            fn=lambda: sum(
                1 for c in conns() if getattr(c, "_writer", None) is None
            ),
        )
        reg.gauge(
            "net_connect_failures",
            "Connect attempts failed across live connections",
            labels,
            fn=lambda: sum(
                getattr(c, "connect_failures", 0) for c in conns()
            ),
        )
        reg.gauge(
            "net_queued_messages",
            "Messages queued across the sender's connections",
            labels,
            fn=lambda: sum(c.queue.qsize() for c in conns()),
        )
        if hasattr(type(sender), "pacing_stalls"):
            reg.gauge(
                "net_broadcast_pacing_stalls",
                "Bounded-pool broadcast chunks that waited for drain",
                labels,
                fn=lambda s=sender: s.pacing_stalls,
            )
        reg.gauge(
            "net_backoff_jitter",
            "Reconnect retries whose backoff sleep was jittered "
            "(stampede-avoided reconnects)",
            labels,
            # asyncio reliable connections count per connection; the
            # native reliable sender keeps one process-wide counter
            fn=lambda s=sender: sum(
                getattr(c, "jittered_retries", 0)
                for c in getattr(s, "_connections", {}).values()
            )
            + getattr(s, "jittered_retries", 0),
        )
        if peers:
            peers = list(peers)
            reg.gauge(
                "net_peers_elided",
                "Peers beyond the per-peer gauge cap (still fully "
                "counted in flow totals and the ranked snapshot block)",
                labels,
                fn=lambda n=max(
                    0, len(peers) - PEER_GAUGE_MAX_COMMITTEE
                ): n,
            )
            for peer_name, address in peers[:PEER_GAUGE_MAX_COMMITTEE]:
                self._register_peer(role, sender, peer_name, address)
            # beyond the gauge cap: no registry instruments, but the
            # snapshot's ranked peer block still tracks the connection
            for peer_name, address in peers[PEER_GAUGE_MAX_COMMITTEE:]:
                short = str(peer_name)[:8]
                self._peer_conns.setdefault(short, []).append(
                    (sender, address)
                )

    def _register_peer(self, role: str, sender, peer_name, address) -> None:
        """Per-peer gauges over one sender's connection to ``address``.
        The connection is looked up lazily (pull model) — senders create
        connections on first send, so it may not exist yet."""
        short = str(peer_name)[:8]
        labels = {**self.labels, "role": role, "peer": short}
        reg = self.registry

        def conn(s=sender, a=address):
            return getattr(s, "_connections", {}).get(a)

        def queued():
            c = conn()
            return c.queue.qsize() if c is not None else 0

        def retrying():
            c = conn()
            return int(c is not None and getattr(c, "_writer", None) is None)

        def failures():
            c = conn()
            return getattr(c, "connect_failures", 0) if c is not None else 0

        def jittered():
            c = conn()
            return getattr(c, "jittered_retries", 0) if c is not None else 0

        reg.gauge(
            "net_peer_backoff_jitter",
            "Jittered reconnect retries toward this peer",
            labels,
            fn=jittered,
        )
        reg.gauge(
            "net_peer_queued",
            "Messages queued toward this peer",
            labels,
            fn=queued,
        )
        reg.gauge(
            "net_peer_retrying",
            "1 while this peer is disconnected (connect-retry/backoff)",
            labels,
            fn=retrying,
        )
        reg.gauge(
            "net_peer_connect_failures",
            "Connect attempts failed toward this peer",
            labels,
            fn=failures,
        )
        self._peer_conns.setdefault(short, []).append((sender, address))

    # ---- snapshot -------------------------------------------------------

    def _net_section(self) -> dict:
        out = {}
        for role, s in self._senders:
            conns = list(getattr(s, "_connections", {}).values())
            entry = {
                "conns": len(conns),
                "queued": sum(c.queue.qsize() for c in conns),
                "retrying": sum(
                    1 for c in conns if getattr(c, "_writer", None) is None
                ),
                "connect_failures": sum(
                    getattr(c, "connect_failures", 0) for c in conns
                ),
                "jittered_retries": sum(
                    getattr(c, "jittered_retries", 0) for c in conns
                )
                + getattr(s, "jittered_retries", 0),
                "evictions": getattr(s, "pool_evictions", 0),
            }
            if hasattr(type(s), "pacing_stalls"):
                entry["pacing_stalls"] = s.pacing_stalls
            out[role] = entry
        if self._peer_conns:
            # rank by flow bytes when the accountant is attached so the
            # top-K block shows the peers that actually matter; the
            # rest are an explicit count, never a silent drop
            shorts = list(self._peer_conns)
            flow_bytes: dict[str, int] = {}
            if self.flows is not None and self.flows.enabled:
                flow_bytes = {
                    p: tx + rx for p, tx, rx in self.flows.peer_totals()
                }
                shorts.sort(key=lambda s: (-flow_bytes.get(s, 0), s))
            shown = shorts[:PEER_GAUGE_MAX_COMMITTEE]
            peer_out = {}
            for short in shown:
                queued = failures = retrying = 0
                for sender, address in self._peer_conns[short]:
                    c = getattr(sender, "_connections", {}).get(address)
                    if c is None:
                        continue
                    queued += c.queue.qsize()
                    failures += getattr(c, "connect_failures", 0)
                    retrying = max(
                        retrying,
                        int(getattr(c, "_writer", None) is None),
                    )
                peer_out[short] = {
                    "queued": queued,
                    "retrying": retrying,
                    "connect_failures": failures,
                }
                if short in flow_bytes:
                    peer_out[short]["bytes"] = flow_bytes[short]
            out["peer"] = peer_out
            out["peers_elided"] = len(shorts) - len(shown)
        return out

    def snapshot(self) -> dict:
        """The ``Telemetry snapshot:`` document.  A strict superset of
        ``WorkStats.to_json()`` (the ``Work stats:`` scrape contract) —
        its keys stay at the top level."""
        doc: dict = {"node": self.node}
        if self.workstats is not None:
            doc.update(self.workstats.to_json())
        doc["trace"] = self.trace.to_json()
        if self._senders:
            doc["net"] = self._net_section()
        for name, fn in self._sections.items():
            try:
                doc[name] = fn()
            except Exception as e:  # noqa: BLE001 — snapshots never throw
                doc[name] = {"error": str(e)}
        return doc


__all__ = [
    "Counter",
    "FloatCounter",
    "Gauge",
    "Histogram",
    "Registry",
    "TraceRecorder",
    "NodeTelemetry",
    "EDGES",
    "LATENCY_BOUNDS_S",
    "SIZE_BOUNDS",
    "PEER_GAUGE_MAX_COMMITTEE",
    "spans",
    "registry",
    "enable",
    "enabled",
    "set_journal_dir",
    "journal_enabled",
    "journal_dir",
    "for_node",
    "snapshot_all",
    "health_enabled",
    "export_doc",
    "trace_all",
    "reset",
    "maybe_start_server",
]
