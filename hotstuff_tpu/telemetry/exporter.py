"""Telemetry export: the /metrics HTTP endpoint and the snapshot log line.

``MetricsServer`` is a deliberately tiny asyncio HTTP/1.0 responder
(stdlib-only — no aiohttp/prometheus_client dependency): it reads one
request, routes on the path, writes one response, closes.  Prometheus
scrapes tolerate (and default to) connection-per-scrape, so the
single-shot shape is correct, and nothing here can hold fds open
against the node's own connection budget.

Routes:

- ``GET /metrics``  — OpenMetrics 1.0 text exposition (correct
  ``Content-Type``, counter families without / samples with the
  ``_total`` suffix, ``# EOF`` terminator) so real Prometheus scrapers
  work against a node unmodified
- ``GET /snapshot`` — the same JSON document the periodic ``Telemetry
  snapshot:`` log line carries, one object per node in this process
- ``GET /trace``    — the newest completed per-round trace records per
  node (the trace ring buffer, ``telemetry/trace.py``)
- ``GET /delta?since=N`` — incremental health-plane export
  (``telemetry/health.py``): a compact JSON delta frame of the flat
  per-node state (gauges, histograms, state-root cursor) against
  sequence ``N``, or a full frame when ``N`` is unknown — the fleet
  watcher pulls O(changed) per tick, not O(all)

``run_snapshot_logger`` is the periodic per-node task: it samples
event-loop lag (the same probe contract as ``utils/workstats.run_probe``
— the direct host-starvation signal) and logs ``Telemetry snapshot:
{json}`` every ``LOG_INTERVAL``.  The JSON is a strict superset of the
``Work stats:`` document, so the scaling harness's scrape contract is
subsumed, not broken.
"""

from __future__ import annotations

import asyncio
import json
import logging

from .health import DeltaStream

log = logging.getLogger(__name__)

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

LOG_INTERVAL = 5.0
LAG_INTERVAL = 0.05

_HTTP_STATUS = {200: "OK", 404: "Not Found", 405: "Method Not Allowed"}


class MetricsServer:
    """One process-wide scrape endpoint over the shared registry."""

    def __init__(self, registry, host: str = "0.0.0.0", port: int = 0):
        self.registry = registry
        self.host = host
        self.port = port  # 0 = ephemeral; replaced by the bound port
        self._server: asyncio.AbstractServer | None = None
        self._delta = DeltaStream()

    async def start(self) -> "MetricsServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("Telemetry /metrics endpoint listening on port %d", self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # ---- request handling ----------------------------------------------

    def _route(self, method: str, path: str) -> tuple[int, str, str]:
        """(status, content_type, body) for one request."""
        if method != "GET":
            return 405, "text/plain; charset=utf-8", "method not allowed\n"
        path, _, query = path.partition("?")
        if path == "/metrics":
            return (
                200,
                OPENMETRICS_CONTENT_TYPE,
                self.registry.render_openmetrics(),
            )
        if path == "/delta":
            from . import export_doc

            since = -1
            for part in query.split("&"):
                if part.startswith("since="):
                    try:
                        since = int(part[len("since="):])
                    except ValueError:
                        since = -1
            frame = self._delta.frame(export_doc(), since)
            return (
                200,
                "application/json",
                json.dumps(frame, sort_keys=True) + "\n",
            )
        if path == "/snapshot":
            from . import snapshot_all

            return (
                200,
                "application/json",
                json.dumps(snapshot_all(), sort_keys=True) + "\n",
            )
        if path == "/trace":
            from . import trace_all

            return 200, "application/json", json.dumps(trace_all()) + "\n"
        return 404, "text/plain; charset=utf-8", "not found\n"

    async def _handle(self, reader, writer) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5.0)
            parts = request.decode("latin-1", "replace").split()
            method, path = (parts + ["", "/"])[:2]
            # drain headers; a scrape sends few — bound the loop anyway
            for _ in range(100):
                line = await asyncio.wait_for(reader.readline(), timeout=5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            try:
                status, ctype, body = self._route(method, path)
            except Exception:  # noqa: BLE001 — a scrape must never crash
                log.exception("telemetry scrape failed")
                status, ctype, body = 200, "text/plain", "# scrape error\n"
            payload = body.encode()
            writer.write(
                (
                    f"HTTP/1.0 {status} {_HTTP_STATUS.get(status, 'OK')}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + payload
            )
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError, OSError):
            pass
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass


async def run_snapshot_logger(
    tel, logger=None, sample_lag: bool = True
) -> None:
    """Per-node periodic snapshot: ``Telemetry snapshot: {json}`` every
    LOG_INTERVAL seconds.  When ``sample_lag`` (no separate workstats
    probe running), also feeds the loop-lag probe into the node's
    WorkStats so the snapshot's lag keys are live."""
    logger = logger or log
    loop = asyncio.get_running_loop()
    next_log = loop.time() + LOG_INTERVAL
    stats = getattr(tel, "workstats", None)
    while True:
        if sample_lag and stats is not None:
            t0 = loop.time()
            await asyncio.sleep(LAG_INTERVAL)
            lag = max(loop.time() - t0 - LAG_INTERVAL, 0.0)
            stats.lag_samples += 1
            stats.lag_total_s += lag
            stats.lag_max_s = max(stats.lag_max_s, lag)
        else:
            await asyncio.sleep(LOG_INTERVAL / 8)
        if loop.time() >= next_log:
            next_log = loop.time() + LOG_INTERVAL
            try:
                doc = json.dumps(tel.snapshot(), sort_keys=True)
            except Exception as e:  # noqa: BLE001 — never kill the task
                logger.warning("telemetry snapshot failed: %s", e)
                continue
            # NOTE: this log entry is scraped (benchmark/logs.py) — it
            # subsumes the 'Work stats:' document (superset of its keys).
            logger.info("Telemetry snapshot: %s", doc)


__all__ = [
    "MetricsServer",
    "run_snapshot_logger",
    "LOG_INTERVAL",
    "OPENMETRICS_CONTENT_TYPE",
]
