"""Central span-stage and journal-edge taxonomy (ISSUE 12).

Every span stage name (``telemetry/spans.py``) and every journal edge
name (``telemetry/journal.py`` records) is registered HERE, and here
only.  ``benchmark/traces.py`` renders from these same tables, so an
edge that isn't registered is a **lint error**
(``hotstuff_tpu/analysis`` rule ``taxonomy-registry``) instead of a
silently-empty Perfetto track.

Adding an edge or stage is a two-line change: record it at the call
site, register it here (with the rendering group it belongs to).  The
lint rule cross-checks both directions: call sites must use registered
names, and ``traces.py`` must route every registered group.

This module is a pure-constant leaf: stdlib only, no imports, safe for
``benchmark/traces.py`` (which otherwise has no node-runtime
dependency) and for the analysis plane running in a bare CI venv.
"""

# ---- verify-pipeline span stages (telemetry/spans.py) ----------------------

#: leaf stages, in pipeline order — the canonical waterfall rows; spans
#: with other names (parents, ad-hoc) are recorded but never summed
SPAN_LEAF_STAGES: tuple = (
    "coalesce.wait",
    "native.pack",
    "route.decide",
    "pipeline.wait",
    "stage.pack",
    "stage.slot_wait",
    "queue.wait",
    "flatten",
    "prepare",
    "dispatch",
    "device.execute",
    "mesh.psum",
    "readback",
    "host.verify",
    "host.pairing",
    "verdict.fanout",
)

#: frame spans: overlap the leaves, excluded from waterfall sums
SPAN_PARENT_STAGES: tuple = (
    "e2e",
    "dispatch.wall",
    "agg.verify",
    "scheme.route",
)

#: value annotations: span records whose duration field encodes a VALUE
#: (e.g. in-flight wave depth), excluded from waterfall sums and
#: rendered as counter series
SPAN_ANNOTATION_STAGES: tuple = ("pipeline.occupancy",)

#: BLS-aggregation detail stages (crypto/bls/service.py, tpu/bls.py):
#: sub-phases of the ``agg.verify`` parent frame — recorded and
#: histogrammed, never waterfall rows.  Surfaced as unregistered drift
#: by the taxonomy-registry lint the day it landed (ISSUE 12).
SPAN_AGG_STAGES: tuple = (
    "agg.gather",
    "agg.keysum",
    "agg.pairing",
    "agg.accumulate",
    "agg.snapshot",
)

#: every registered span stage name (what ``span("...")`` /
#: ``rec.add("...")`` call sites are checked against)
SPAN_STAGES: frozenset = frozenset(
    SPAN_LEAF_STAGES
    + SPAN_PARENT_STAGES
    + SPAN_ANNOTATION_STAGES
    + SPAN_AGG_STAGES
)

# ---- journal edges (telemetry/journal.py records) --------------------------

#: block-lifecycle edges: ``traces.py`` folds these into per-block
#: cross-node timelines (propose anchor, receive fan-out, vote, QC,
#: commit)
BLOCK_EDGES: tuple = (
    "propose",
    "recv.propose",
    "vote.send",
    "recv.vote",
    "qc.form",
    "qc",
    "commit",
)

#: control-plane edges: journaled for the SUMMARY/debugging but
#: excluded from per-block reconstruction (several carry no digest)
CONTROL_EDGES: tuple = (
    "tc",
    "round.enter",
    "recv.timeout",
    "recv.tc",
    "sync.req",
    "sync.reply",
    "sync.done",
    "sync.expire",
    "sync.serve",
    "sync.manifest",
    "sync.chunk",
    "sync.adopt",
    "recv.sync_req",
    "recv.state_req",
    "state.apply",
    "recv.reconfig",
)

#: producer-channel edges: leader-side payload wait attribution
PAYLOAD_EDGES: tuple = ("recv.producer", "payload.first")

#: admission-plane edges: value records (shed count / credit window in
#: the ``u`` field), rendered as the ingest-plane track
INGEST_EDGES: tuple = ("ingest.shed", "ingest.credit")

#: zero-copy ingest metrics (ISSUE 20): registry counter names for
#: waves the verify service adopted straight from a native staging
#: arena vs. vote-overlapping waves that had to fall back to the
#: Python flatten path (disjoint non-vote waves count as neither).
#: The hit rate zc/(zc+fb) is surfaced on the verify stats line
#: (``zc=``/``fb=``) and asserted >=0.9 by scripts/ingest_check.py.
INGEST_COUNTERS: tuple = ("ingest_zero_copy_waves", "ingest_fallback_waves")

#: standalone edges: local timeout complaints, the profiler fan-out
#: record (stage in ``p``, duration in ``u``), and each ring segment's
#: identity line
MISC_EDGES: tuple = ("timeout", "span", "meta")

#: dynamic edge families: the chaos plane journals ``fault.<kind>``,
#: the adversary plane ``byz.<kind>``, the health plane
#: ``health.<kind>`` (telemetry/health.py detector incidents, open/close
#: in the peer field; the fleet-level ``health.epoch_skew`` rides the
#: same family) with scenario-/detector-defined kinds, and the live
#: reconfiguration plane ``reconfig.<step>`` (submit/commit/activate/
#: retire/link — consensus/core.py, reconfig.py); an f-string edge is
#: lint-legal iff its constant prefix is listed here
FAULT_PREFIX = "fault."
BYZ_PREFIX = "byz."
INGEST_PREFIX = "ingest."
HEALTH_PREFIX = "health."
RECONFIG_PREFIX = "reconfig."
NET_PREFIX = "net."
JOURNAL_EDGE_PREFIXES: tuple = (
    FAULT_PREFIX,
    BYZ_PREFIX,
    HEALTH_PREFIX,
    RECONFIG_PREFIX,
    NET_PREFIX,
)

# ---- wire-level flow classes (telemetry/flows.py) --------------------------

#: every message class the flow accounting plane charges a frame to —
#: derived from the wire-tag taxonomy (consensus/wire.py first byte;
#: ``telemetry/flows.py`` owns the byte->class map, and
#: ``tests/test_flows.py`` cross-checks it against the live wire
#: constants so tag drift is a test failure, not a silently-mislabelled
#: flow).  ``qc-compact`` wire cost rides inside ``propose`` frames and
#: is reported from the aggregator telemetry next to these classes.
FLOW_CLASSES: tuple = (
    "propose",
    "vote",
    "timeout",
    "tc",
    "sync-req",
    "producer-v1",
    "producer-v2",
    "ingest-ack",
    "state-sync",
    "reconfig",
    "ack",
    "other",
)

#: flow directions: every accounted frame is charged to exactly one
#: ``(peer, direction, class)`` flow at its send and its receive site
FLOW_DIRECTIONS: tuple = ("tx", "rx")

#: every registered static journal edge name (what ``journal.record``
#: call sites are checked against)
JOURNAL_EDGES: frozenset = frozenset(
    BLOCK_EDGES + CONTROL_EDGES + PAYLOAD_EDGES + INGEST_EDGES + MISC_EDGES
)


# ---- commit critical-path stages (telemetry/critpath.py) -------------------

#: critical-path stage taxonomy: every stage the commit critical-path
#: engine (``telemetry/critpath.py``) attributes latency to.  Two-round
#: chained-HotStuff commit means the per-round stages (net.propose,
#: vote.local, net.vote, agg.form) each appear once per chained round
#: and sum into one bucket.  ``unattributed`` is the residual between
#: the measured propose->commit wall and the sum of reconstructed
#: segments — rendered, never hidden.
CRITPATH_STAGES: tuple = (
    "ingest.wait",  # leader payload wait: producer recv -> propose
    "net.propose",  # propose broadcast -> quorum-th replica receive
    "vote.local",  # replica receive -> vote send (verify + sign)
    "net.vote",  # vote send -> receive at the aggregating node
    "agg.form",  # quorum-th vote receive -> QC assembled
    "lead.handoff",  # QC formed -> next-round proposal broadcast
    "commit.exec",  # chained QC formed -> commit observed at the node
    "unattributed",  # residual: measured total minus reconstructed sum
)

#: regime classification: which stage buckets vote for which regime —
#: the argmax group over attributed milliseconds names the run
CRITPATH_REGIMES: dict = {
    "ingest-bound": ("ingest.wait",),
    "network-bound": ("net.propose", "net.vote", "commit.exec"),
    "verify-bound": ("vote.local",),
    "aggregation-bound": ("agg.form", "lead.handoff"),
}


def is_registered_edge(name: str) -> bool:
    """Is ``name`` a registered journal edge (static or dynamic)?"""
    return name in JOURNAL_EDGES or name.startswith(JOURNAL_EDGE_PREFIXES)


def is_registered_stage(name: str) -> bool:
    """Is ``name`` a registered verify-pipeline span stage?"""
    return name in SPAN_STAGES


__all__ = [
    "SPAN_LEAF_STAGES",
    "SPAN_PARENT_STAGES",
    "SPAN_ANNOTATION_STAGES",
    "SPAN_AGG_STAGES",
    "SPAN_STAGES",
    "BLOCK_EDGES",
    "CONTROL_EDGES",
    "PAYLOAD_EDGES",
    "INGEST_EDGES",
    "INGEST_COUNTERS",
    "MISC_EDGES",
    "FAULT_PREFIX",
    "BYZ_PREFIX",
    "INGEST_PREFIX",
    "HEALTH_PREFIX",
    "RECONFIG_PREFIX",
    "NET_PREFIX",
    "FLOW_CLASSES",
    "FLOW_DIRECTIONS",
    "JOURNAL_EDGE_PREFIXES",
    "JOURNAL_EDGES",
    "CRITPATH_STAGES",
    "CRITPATH_REGIMES",
    "is_registered_edge",
    "is_registered_stage",
]
