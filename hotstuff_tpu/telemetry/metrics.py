"""Telemetry instruments: counters, gauges, log-bucket histograms.

Design constraints (ISSUE 1 tentpole):

- **No allocation on the hot path.** ``Histogram.observe`` is a bisect
  over a precomputed bound tuple plus integer increments into a
  preallocated count list; ``Counter.inc`` is one integer add.  All
  rendering/percentile work happens at scrape/snapshot time, off the
  consensus path.
- **Pull-model gauges.** Component state that already exists (queue
  depths, pool occupancy, buffer sizes) is read lazily by a callback at
  scrape time instead of being pushed per event — enabling telemetry
  must not add writes to paths that only needed reads.
- **Fixed log-spaced buckets.** One global bucket ladder for latency
  histograms (100 us .. ~200 s, factor 2) so every edge histogram is
  comparable and the Prometheus exposition stays small and static.

Everything here is stdlib-only and independent of the consensus stack;
``registry.py``-style aggregation lives in ``Registry`` below.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Callable, Iterator

# Log-spaced latency bucket upper bounds, in SECONDS: 100 us doubling up
# to ~209 s (22 finite buckets + overflow).  Spans device-verify sub-ms
# latencies through worst-case view-change backoff (timeout_cap 60 s).
LATENCY_BOUNDS_S: tuple[float, ...] = tuple(1e-4 * 2**i for i in range(22))

# Log-spaced size bucket upper bounds (dimensionless): 1, 2, 4 .. 2^19.
# The batch-size / queue-depth ladder.
SIZE_BOUNDS: tuple[float, ...] = tuple(float(2**i) for i in range(20))


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "help", "labels", "value")

    kind = "counter"

    def __init__(self, name: str, help_: str = "", labels: dict | None = None):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def to_json(self):
        return self.value

    def samples(self) -> Iterator[tuple[str, dict, float]]:
        yield self.name, self.labels, self.value


class FloatCounter(Counter):
    """Monotonic float accumulator (wall-clock seconds split lines)."""

    __slots__ = ()

    def __init__(self, name: str, help_: str = "", labels: dict | None = None):
        super().__init__(name, help_, labels)
        self.value = 0.0

    def add(self, v: float) -> None:
        self.value += v

    def to_json(self):
        return round(self.value, 6)


class Gauge:
    """Instantaneous value — either set pushed (``set``) or pulled from a
    zero-argument callback at scrape time (``fn``)."""

    __slots__ = ("name", "help", "labels", "_value", "fn")

    kind = "gauge"

    def __init__(
        self,
        name: str,
        help_: str = "",
        labels: dict | None = None,
        fn: Callable[[], float] | None = None,
    ):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self._value = 0.0
        self.fn = fn

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:  # noqa: BLE001 — a scrape must never throw
                return -1.0
        return self._value

    def to_json(self):
        v = self.value
        return round(v, 6) if isinstance(v, float) else v

    def samples(self) -> Iterator[tuple[str, dict, float]]:
        yield self.name, self.labels, self.value


class Histogram:
    """Fixed-bucket histogram with log-spaced bounds.

    ``observe`` does no allocation: index = bisect over the bound tuple,
    then three scalar updates.  Percentiles are estimated at snapshot
    time from the cumulative bucket counts (upper-bound estimate — the
    reported pXX is the bucket ceiling, conservative by at most one
    bucket factor).
    """

    __slots__ = ("name", "help", "labels", "bounds", "counts", "count", "sum", "max")

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str = "",
        labels: dict | None = None,
        bounds: tuple[float, ...] = LATENCY_BOUNDS_S,
    ):
        self.name = name
        self.help = help_
        self.labels = labels or {}
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: overflow (+Inf)
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        if v > self.max:
            self.max = v

    def percentile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile (0 < q <= 1)."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                if i < len(self.bounds):
                    return self.bounds[i]
                return self.max  # overflow bucket
        return self.max

    def to_json(self, scale: float = 1e3, unit: str = "ms") -> dict:
        """Compact summary (default: seconds -> milliseconds)."""
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            f"mean_{unit}": round(self.sum / self.count * scale, 3),
            f"p50_{unit}": round(self.percentile(0.5) * scale, 3),
            f"p99_{unit}": round(self.percentile(0.99) * scale, 3),
            f"max_{unit}": round(self.max * scale, 3),
        }

    def samples(self) -> Iterator[tuple[str, dict, float]]:
        cum = 0
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            yield (
                self.name + "_bucket",
                {**self.labels, "le": _fmt(bound)},
                cum,
            )
        yield self.name + "_bucket", {**self.labels, "le": "+Inf"}, self.count
        yield self.name + "_sum", self.labels, self.sum
        yield self.name + "_count", self.labels, self.count


def _fmt(v: float) -> str:
    """Shortest exact-enough label for a bucket bound."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class Registry:
    """Ordered collection of instruments, rendered to Prometheus text
    exposition format or a JSON snapshot.

    Instruments are keyed by (name, sorted label items) — registering
    the same key twice returns the existing instrument so process-wide
    singletons (the async verify service) and per-node components can
    idempotently self-register.
    """

    def __init__(self, prefix: str = "hotstuff"):
        self.prefix = prefix
        self._instruments: dict[tuple, object] = {}

    def _key(self, name: str, labels: dict | None) -> tuple:
        return (name, tuple(sorted((labels or {}).items())))

    def _register(self, cls, name, help_, labels, **kw):
        full = f"{self.prefix}_{name}" if self.prefix else name
        key = self._key(full, labels)
        inst = self._instruments.get(key)
        if inst is None:
            inst = cls(full, help_, labels, **kw)
            self._instruments[key] = inst
        return inst

    def counter(self, name: str, help_: str = "", labels: dict | None = None) -> Counter:
        return self._register(Counter, name, help_, labels)

    def float_counter(
        self, name: str, help_: str = "", labels: dict | None = None
    ) -> FloatCounter:
        return self._register(FloatCounter, name, help_, labels)

    def gauge(
        self,
        name: str,
        help_: str = "",
        labels: dict | None = None,
        fn: Callable[[], float] | None = None,
    ) -> Gauge:
        g = self._register(Gauge, name, help_, labels)
        if fn is not None:
            g.fn = fn
        return g

    def histogram(
        self,
        name: str,
        help_: str = "",
        labels: dict | None = None,
        bounds: tuple[float, ...] = LATENCY_BOUNDS_S,
    ) -> Histogram:
        return self._register(Histogram, name, help_, labels, bounds=bounds)

    def __iter__(self):
        return iter(self._instruments.values())

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: list[str] = []
        seen_meta: set[str] = set()
        for inst in self._instruments.values():
            if inst.name not in seen_meta:
                seen_meta.add(inst.name)
                if inst.help:
                    lines.append(f"# HELP {inst.name} {inst.help}")
                lines.append(f"# TYPE {inst.name} {inst.kind}")
            for sample_name, labels, value in inst.samples():
                lines.append(_sample_line(sample_name, labels, value))
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """OpenMetrics 1.0 text exposition.

        Differences from the 0.0.4 format that real scrapers enforce:
        counter *metadata* names the family without the ``_total``
        suffix while every counter *sample* carries it (instruments
        already named ``*_total`` are not double-suffixed), and the
        exposition terminates with ``# EOF``.
        """
        lines: list[str] = []
        seen_meta: set[str] = set()
        for inst in self._instruments.values():
            family = inst.name
            if inst.kind == "counter" and family.endswith("_total"):
                family = family[: -len("_total")]
            if family not in seen_meta:
                seen_meta.add(family)
                if inst.help:
                    lines.append(f"# HELP {family} {inst.help}")
                lines.append(f"# TYPE {family} {inst.kind}")
            for sample_name, labels, value in inst.samples():
                if inst.kind == "counter" and not sample_name.endswith(
                    "_total"
                ):
                    sample_name += "_total"
                lines.append(_sample_line(sample_name, labels, value))
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _sample_line(sample_name: str, labels: dict, value) -> str:
    if labels:
        lbl = ",".join(f'{k}="{_escape(str(v))}"' for k, v in labels.items())
        return f"{sample_name}{{{lbl}}} {_num(value)}"
    return f"{sample_name} {_num(value)}"


def _escape(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _num(v) -> str:
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


__all__ = [
    "Counter",
    "FloatCounter",
    "Gauge",
    "Histogram",
    "Registry",
    "LATENCY_BOUNDS_S",
    "SIZE_BOUNDS",
]
