"""Commit critical-path engine: where a committed block's wall-clock goes.

The flight recorder (journal.py) gives per-node event streams and
``benchmark/traces.py`` merges them into clock-aligned per-block
timelines — but a timeline is not an attribution.  This module walks,
for every committed block, the causal chain that HAD to complete before
the commit could fire under 2-chain chained HotStuff:

    producer recv -> propose(B) -> quorum-th replica recv -> that
    voter's local verify+sign -> vote net edge -> quorum-th vote
    arrival at the next leader -> QC(B) assembled -> next proposal
    broadcast (the QC rides it) -> [same per-round chain for B'] ->
    QC(B') assembled -> commit(B) observed at the slowest node

and charges each hop to one stage of the registered taxonomy
(``CRITPATH_STAGES`` in taxonomy.py — the same registry the
taxonomy-registry lint enforces for journal edges).  The two chained
rounds share stage buckets: ``net.propose`` is the sum of both rounds'
proposal fan-outs, and so on.  Whatever the reconstruction cannot
anchor on journaled events lands in ``unattributed`` — rendered,
never hidden (the coverage figure is the engine's own honesty metric).

Pure and unit-testable: stdlib + the constant-leaf taxonomy only.  The
input is duck-typed (anything with ``.blocks`` / ``.nodes`` /
``.payload_waits`` shaped like ``benchmark.traces.TraceSet``), so
fixture-journal tests and the deterministic simulator feed it without
the node runtime.

Consumers:

- ``python -m benchmark critpath`` (benchmark/critpath.py): the
  "+ CRITPATH" SUMMARY block, the Perfetto critical-path track, and
  the attribution-diff regression gate (``--diff``).
- ``hotstuff_tpu/sim``: ``run_schedule`` attaches per-seed attribution
  to its verdict (same seed => identical attribution).
- ``telemetry/health.py``: the on-node HealthMonitor ticks
  :func:`rolling_attribution` over the trace recorder's recent commits
  and feeds the ``crit_regime_shift`` detector plus the DOMINANT-STAGE
  column in ``benchmark watch``.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field

from .taxonomy import CRITPATH_REGIMES, CRITPATH_STAGES

#: default attribution-diff tolerance: a stage's share of commit
#: latency may grow this many percentage points before --diff fails
#: (HOTSTUFF_CRITPATH_DIFF_PP overrides at the CLI)
DIFF_SHARE_PP = 10.0

#: a diffed stage is ignored below this share on BOTH sides — tiny
#: stages flap in percentage terms without moving the commit latency
DIFF_MIN_SHARE = 0.02

#: on-node rolling attribution: which local trace-recorder edge maps to
#: which regime (a coarse single-node proxy for the cross-node engine —
#: propose->vote rides the proposal net hop + verify, vote->qc is
#: aggregation, qc->commit is the chained round + QC propagation)
LOCAL_EDGE_REGIME = {
    "propose_to_vote": "verify-bound",
    "vote_to_qc": "aggregation-bound",
    "qc_to_commit": "network-bound",
}


def _pctl(values: list[float], q: float) -> float:
    """Nearest-rank percentile over a non-empty list (q in [0, 100])."""
    xs = sorted(values)
    if not xs:
        return 0.0
    k = max(0, min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[k]


@dataclass
class Segment:
    """One hop of a commit's critical path.  ``w_start``/``w_end`` are
    offset-corrected wall ns when the hop is anchored on journaled
    events (the Perfetto track renders those), None for derived
    estimates (ingest.wait)."""

    stage: str
    ms: float
    detail: str = ""
    w_start: int | None = None
    w_end: int | None = None


@dataclass
class CommitPath:
    """One committed block's reconstructed critical path."""

    digest: str
    round: int
    node: str  # last node to commit (the path ends there)
    total_ms: float  # measured: ingest estimate + propose -> commit
    stages: dict = field(default_factory=dict)  # stage -> ms (attributed)
    segments: list = field(default_factory=list)  # [Segment], path order
    coverage: float = 0.0  # attributed / total (capped at 1)

    @property
    def dominant(self) -> str:
        if not self.stages:
            return "unattributed"
        attributed = sum(self.stages.values())
        residual = max(0.0, self.total_ms - attributed)
        best = max(self.stages, key=lambda s: self.stages[s])
        if residual > self.stages[best]:
            return "unattributed"
        return best


@dataclass
class CritPathReport:
    """The run-level aggregation ``analyze`` returns."""

    commits: list = field(default_factory=list)  # [CommitPath]
    regime: str = "unknown"
    coverage: float = 0.0  # mean per-commit attributed fraction
    journal_coverage: float = 1.0
    dropped_records: int = 0
    stage_totals: dict = field(default_factory=dict)  # stage -> summed ms

    def attribution(self) -> dict:
        """The machine-readable attribution document: bench.py's
        "critpath" block, the perfgate guards, SimVerdict.attribution,
        and both sides of the --diff gate all speak this shape."""
        totals = [c.total_ms for c in self.commits]
        measured = sum(totals)
        stages: dict[str, dict] = {}
        for stage in CRITPATH_STAGES:
            if stage == "unattributed":
                continue
            per_commit = [c.stages.get(stage, 0.0) for c in self.commits]
            summed = sum(per_commit)
            if not summed:
                continue
            stages[stage] = {
                "p50_ms": round(_pctl(per_commit, 50), 3),
                "p99_ms": round(_pctl(per_commit, 99), 3),
                "share": round(summed / measured, 4) if measured else 0.0,
            }
        dominant = Counter(c.dominant for c in self.commits)
        return {
            "commits": len(self.commits),
            "p50_ms": round(_pctl(totals, 50), 3),
            "p99_ms": round(_pctl(totals, 99), 3),
            "coverage_pct": round(100.0 * self.coverage, 1),
            "journal_coverage_pct": round(100.0 * self.journal_coverage, 1),
            "regime": self.regime,
            "stages": stages,
            "dominant": dict(dominant),
        }


def _quorum(n: int) -> int:
    """2f+1 for n = 3f+1 committees (n - f in general)."""
    return n - (n - 1) // 3 if n else 0


def _kth_smallest(values: list, k: int):
    """k-th smallest (1-based), clamped into the available range."""
    if not values:
        return None
    xs = sorted(values)
    return xs[max(0, min(len(xs), k) - 1)]


def _decompose_round(
    info: dict, quorum: int, segments: list, stages: dict
) -> int | None:
    """Attribute propose -> QC-formed for one block's round, appending
    anchored segments and summing stage buckets.  Returns the QC
    formation wall (corrected ns) — falling back to the first high-QC
    adoption when the qc.form edge is missing — or None when even that
    is unknown.  Missing intermediate edges shrink attribution (the
    residual lands in unattributed), they never fabricate time."""
    if info["propose"] is None:
        return None
    _, w0 = info["propose"]
    rnd = info["round"]
    qcf = info.get("qc_form") or info.get("qc")
    w_qc = qcf[2] if qcf is not None else None

    def charge(stage: str, start: int, end: int, detail: str) -> None:
        ms = (end - start) / 1e6
        if ms < 0:
            return  # clock-correction artifact: skip, never negative-charge
        stages[stage] = stages.get(stage, 0.0) + ms
        segments.append(
            Segment(stage, ms, detail, w_start=start, w_end=end)
        )

    # propose -> quorum-th replica receive (the leader holds the block
    # at w0, so quorum-1 network arrivals complete the proposal fan-out)
    recvs = info["recv"]
    q_recv = _kth_smallest([w for _, w in recvs.values()], quorum - 1)
    cursor = w0
    if q_recv is not None:
        charge(
            "net.propose", w0, q_recv, f"r{rnd} propose fan-out"
        )
        cursor = q_recv

    # the critical voter: the one whose vote ARRIVED quorum-th at the
    # aggregating (next-leader) node — its chain is the binding one
    rv = info.get("recv_vote") or {}
    v_star, w_rv = None, None
    if rv:
        arrivals = sorted(
            (w, voter) for voter, (_n, _m, w) in rv.items()
        )
        k = max(0, min(len(arrivals), quorum - 1) - 1)
        w_rv, v_star = arrivals[k]

    if v_star is not None:
        got = recvs.get(v_star)
        vote = info["vote_send"].get(v_star)
        if got is not None and vote is not None:
            charge(
                "vote.local",
                got[1],
                vote[1],
                f"r{rnd} verify+sign at {v_star}",
            )
            charge(
                "net.vote", vote[1], w_rv, f"r{rnd} vote from {v_star}"
            )
            cursor = w_rv
        elif vote is not None:
            charge(
                "net.vote", vote[1], w_rv, f"r{rnd} vote from {v_star}"
            )
            cursor = w_rv
        else:
            cursor = max(cursor, w_rv)
    if w_qc is not None and cursor is not None:
        charge("agg.form", cursor, w_qc, f"r{rnd} QC assembly")
    return w_qc


def analyze(traces, quorum: int | None = None) -> CritPathReport:
    """Reconstruct and attribute every committed block's critical path.

    ``traces``: a ``benchmark.traces.TraceSet`` (or any object with the
    same ``blocks`` / ``nodes`` / ``payload_waits`` surface).  ``quorum``
    defaults to 2f+1 for the journaled committee size."""
    blocks: dict[str, dict] = traces.blocks
    if quorum is None:
        quorum = _quorum(len(traces.nodes))
    by_round: dict[int, str] = {}
    for digest, info in blocks.items():
        if info["propose"] is not None:
            by_round.setdefault(info["round"], digest)

    # producer recv -> propose is journaled per PAYLOAD digest and
    # cannot be joined to a block digest; charge the run-median wait as
    # the per-commit ingest estimate (documented as such)
    waits = sorted(getattr(traces, "payload_waits", ()) or ())
    ingest_ms = waits[len(waits) // 2] if waits else 0.0

    report = CritPathReport()
    for digest, info in sorted(
        blocks.items(), key=lambda kv: kv[1]["round"]
    ):
        if not info["commit"] or info["propose"] is None:
            continue
        _, w0 = info["propose"]
        node, (_, w_commit) = max(
            info["commit"].items(), key=lambda kv: kv[1][1]
        )
        if w_commit < w0:
            continue  # irrecoverable clock damage: skip the block
        path = CommitPath(
            digest=digest,
            round=info["round"],
            node=node,
            total_ms=ingest_ms + (w_commit - w0) / 1e6,
        )
        if ingest_ms:
            path.stages["ingest.wait"] = ingest_ms
            path.segments.append(
                Segment(
                    "ingest.wait", ingest_ms, "median producer wait"
                )
            )
        w_qc = _decompose_round(info, quorum, path.segments, path.stages)

        # the 2-chain: B commits when the QC for the DIRECT successor
        # round forms — hand off to that leader and charge its round
        nxt = by_round.get(info["round"] + 1)
        w_qc2 = None
        if w_qc is not None and nxt is not None:
            ninfo = blocks[nxt]
            _, w1 = ninfo["propose"]
            if w1 >= w_qc:
                ms = (w1 - w_qc) / 1e6
                path.stages["lead.handoff"] = (
                    path.stages.get("lead.handoff", 0.0) + ms
                )
                path.segments.append(
                    Segment(
                        "lead.handoff",
                        ms,
                        f"QC r{info['round']} -> propose r{ninfo['round']}",
                        w_start=w_qc,
                        w_end=w1,
                    )
                )
            w_qc2 = _decompose_round(
                ninfo, quorum, path.segments, path.stages
            )
        if w_qc2 is not None and w_commit >= w_qc2:
            ms = (w_commit - w_qc2) / 1e6
            path.stages["commit.exec"] = (
                path.stages.get("commit.exec", 0.0) + ms
            )
            path.segments.append(
                Segment(
                    "commit.exec",
                    ms,
                    f"chained QC -> commit at {node}",
                    w_start=w_qc2,
                    w_end=w_commit,
                )
            )
        attributed = sum(path.stages.values())
        path.coverage = (
            min(1.0, attributed / path.total_ms) if path.total_ms else 0.0
        )
        report.commits.append(path)

    for c in report.commits:
        for stage, ms in c.stages.items():
            report.stage_totals[stage] = (
                report.stage_totals.get(stage, 0.0) + ms
            )
    if report.commits:
        report.coverage = sum(c.coverage for c in report.commits) / len(
            report.commits
        )
    merge_stats = getattr(traces, "merge_stats", None) or {}
    report.dropped_records = merge_stats.get("dropped", 0)
    jc = getattr(traces, "journal_coverage", None)
    report.journal_coverage = jc() if callable(jc) else 1.0
    report.regime = classify_regime(report.stage_totals)
    return report


def classify_regime(stage_totals: dict) -> str:
    """Name the run's binding constraint: the regime whose stage group
    holds the largest share of attributed milliseconds."""
    scores = {
        regime: sum(stage_totals.get(s, 0.0) for s in group)
        for regime, group in CRITPATH_REGIMES.items()
    }
    if not any(scores.values()):
        return "unknown"
    return max(sorted(scores), key=lambda r: scores[r])


# ---- rendering -------------------------------------------------------------


def render(report: CritPathReport) -> str:
    """The "+ CRITPATH" SUMMARY block."""
    lines = [" + CRITPATH (commit critical path):\n"]
    att = report.attribution()
    if not report.commits:
        lines.append(" No committed blocks reconstructed.\n")
        return "".join(lines)
    lines.append(
        f" Commits attributed: {att['commits']};"
        f" stage coverage {att['coverage_pct']:.0f}%"
        f" of measured commit latency\n"
    )
    drop_note = (
        f" ({report.dropped_records} records rotated away)"
        if report.dropped_records
        else ""
    )
    lines.append(
        f" Journal coverage: {att['journal_coverage_pct']:.0f}%"
        f"{drop_note}\n"
    )
    lines.append(
        f" Commit latency: p50 {att['p50_ms']:.2f} ms"
        f"  p99 {att['p99_ms']:.2f} ms;"
        f" regime: {att['regime']}\n"
    )
    for stage in CRITPATH_STAGES:
        entry = att["stages"].get(stage)
        if entry is None:
            continue
        lines.append(
            f"   {stage + ':':<14} p50 {entry['p50_ms']:7.2f} ms"
            f"  p99 {entry['p99_ms']:7.2f} ms"
            f"  share {100.0 * entry['share']:5.1f}%\n"
        )
    total = sum(att["dominant"].values())
    if total:
        top = ", ".join(
            f"{stage} {100.0 * n / total:.0f}%"
            for stage, n in Counter(att["dominant"]).most_common(4)
        )
        lines.append(f" Dominant stage per commit: {top}\n")
    slowest = sorted(
        (
            (seg.ms, c.round, seg)
            for c in report.commits
            for seg in c.segments
        ),
        key=lambda t: -t[0],
    )[:5]
    if slowest:
        lines.append(" Slowest edges:\n")
        for ms, rnd, seg in slowest:
            lines.append(
                f"   {ms:8.2f} ms  {seg.stage:<13} {seg.detail}\n"
            )
    return "".join(lines)


# ---- attribution diff (the regression gate) --------------------------------


def diff(
    current: dict,
    reference: dict,
    share_pp: float = DIFF_SHARE_PP,
    min_share: float = DIFF_MIN_SHARE,
) -> list[str]:
    """Compare two attribution documents; return regression lines
    (empty = pass).  A stage regresses when its SHARE of commit latency
    grows more than ``share_pp`` percentage points over the reference —
    catching "same scalar, different shape" drifts the latency ratchet
    is blind to.  Stages below ``min_share`` on both sides are noise
    and ignored; stages or whole documents missing on either side are
    skipped (skip-if-missing, like the perfgate guards)."""
    fails: list[str] = []
    cur_stages = (current or {}).get("stages") or {}
    ref_stages = (reference or {}).get("stages") or {}
    if not cur_stages or not ref_stages:
        return fails
    for stage, cur in cur_stages.items():
        ref = ref_stages.get(stage)
        cur_share = float(cur.get("share", 0.0))
        ref_share = float(ref.get("share", 0.0)) if ref else 0.0
        if cur_share < min_share and ref_share < min_share:
            continue
        growth_pp = 100.0 * (cur_share - ref_share)
        if growth_pp > share_pp:
            fails.append(
                f"critpath.{stage}.share grew"
                f" {100.0 * ref_share:.1f}% -> {100.0 * cur_share:.1f}%"
                f" (+{growth_pp:.1f}pp > {share_pp:.1f}pp allowed)"
            )
    return fails


# ---- on-node rolling attribution (health plane) ----------------------------


def rolling_attribution(entries) -> dict | None:
    """Coarse per-node attribution over the trace recorder's recent
    commit entries (telemetry/trace.py ring dicts) — no cross-node
    merge exists on-node, so this classifies from the three local
    lifecycle edges.  Returns None below a minimal sample count (the
    detector must not flap on one commit)."""
    entries = [
        e
        for e in (entries or ())
        if e.get("propose_to_commit_ms") is not None
    ]
    if len(entries) < 4:
        return None
    edges_ms = {}
    for edge in LOCAL_EDGE_REGIME:
        vals = [
            e[f"{edge}_ms"]
            for e in entries
            if e.get(f"{edge}_ms") is not None
        ]
        if vals:
            edges_ms[edge] = sum(vals) / len(vals)
    if not edges_ms:
        return None
    dominant = max(sorted(edges_ms), key=lambda k: edges_ms[k])
    return {
        "samples": len(entries),
        "dominant": dominant,
        "regime": LOCAL_EDGE_REGIME[dominant],
        "edges_ms": {k: round(v, 3) for k, v in edges_ms.items()},
    }


__all__ = [
    "DIFF_SHARE_PP",
    "DIFF_MIN_SHARE",
    "LOCAL_EDGE_REGIME",
    "Segment",
    "CommitPath",
    "CritPathReport",
    "analyze",
    "classify_regime",
    "render",
    "diff",
    "rolling_attribution",
]
