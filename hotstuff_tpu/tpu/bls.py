"""BLS12-381 G1 aggregation on TPU — the threshold-variant device path.

Implements the device side of docs/BLS_TPU_DESIGN.md: batched G1 point
aggregation (the psum-shaped reduction that makes BLS QC verification
scale with committee size), leaving the per-QC pairing equality on the
host (crypto/bls/pairing.py), where it is one constant-cost call.

Two design changes vs the original design note, found during
implementation:

1. **Field reduction.**  The note proposed reusing the Ed25519
   fold-constant reduction with a fold *vector* for q.  That does not
   converge: q is not pseudo-Mersenne, so 2^390 mod q is itself 381 bits
   and each fold pass removes only ~9 bits.  Fq instead uses
   **Montgomery arithmetic in CIOS form, vectorized over the batch**:
   30 limbs of 13 bits (30x13 = 390 >= 381) in int32.  The limb
   recurrence is sequential (30 steps, each a full-width batched
   multiply-accumulate) with lazy column accumulators; only the limb-0
   carry is propagated exactly per step (the quotient digit m needs just
   the exact low 13 bits: m = ((t0 & MASK) * mu) & MASK), and a parallel
   carry pass every 8 steps keeps every column inside int32.

2. **Point formulas.**  Jacobian addition needs P==Q / P==-Q / identity
   case analysis, and deciding "h == 0 (mod q)" on device costs a full
   canonicalization per addition.  Instead points are homogeneous
   projective (X : Y : Z) with the **complete addition formulas of
   Renes-Costello-Batina 2015 (Algorithm 7, a = 0, b3 = 12)** — one
   branchless 12-mul formula valid for EVERY input pair in the
   prime-order subgroup, identity (0 : 1 : 0) included.  Aggregation
   inputs are vote signatures, which the CPU layer subgroup-checks on
   deserialization, so completeness holds.

Arithmetic is SIGNED-LOOSE end to end: values are congruences mod q
with limbs a hair over 13 bits (possibly negative — two's-complement
``& MASK`` and arithmetic ``>>`` keep every CIOS step algebraically
exact for signed values), ops end with one parallel carry pass (no
sequential chains, no conditional subtractions, no subtraction pads on
device — tiny XLA graphs), and canonicalization happens once on the
host after the aggregate is fetched (``from_mont_int`` reduces mod q).

Magnitude audit (worst case in point_add): REDC outputs are < 1.5q;
the deepest add/sub/x12 chain is y3 = 12*(REDC - (REDC + REDC)),
magnitude < 12*(1.5q + 3q) = 54q, fed back into mont_mul.  REDC with
R/q = 2^390/q > 500 maps products of such inputs (|ab| < 54q * 20q <
2^773) to outputs < |ab|/R + q < 3.2q — still far below R, so the
recursion is stable.  Limb magnitudes: one carry pass bounds limbs by
2^13 + (peak column >> 13); the x12 scaling peaks columns at ~2^17,
so loose limbs stay < 2^13 + 2^5.  CIOS columns accumulate at most
8 steps * 2 products * (2^13.1)^2 + residual 2^19 < 2^31.

Correctness oracle: the pure-Python backend (crypto/bls/curve.py),
tested in tests/test_tpu_bls.py.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls.curve import G1Point
from ..crypto.bls.fields import P as Q
from ..telemetry import spans as _spans

NLIMBS = 30
LIMB_BITS = 13
MASK = (1 << LIMB_BITS) - 1
NCOLS = NLIMBS + 2  # lazy CIOS accumulator columns (carry headroom)

RADIX = 1 << (NLIMBS * LIMB_BITS)  # 2^390
R_MONT = RADIX % Q
# mu = -q^{-1} mod 2^13 (the CIOS per-limb quotient constant)
MU = (-pow(Q, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

_CARRY_EVERY = 8
B3 = 12  # 3*b for y^2 = x^3 + 4


def _int_to_limbs(x: int, n: int = NLIMBS) -> np.ndarray:
    out = np.zeros(n, np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= LIMB_BITS
    assert x == 0
    return out


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(arr.tolist()))


Q_LIMBS = _int_to_limbs(Q)
Q_EXT = np.concatenate([Q_LIMBS, np.zeros(NCOLS - NLIMBS, np.int32)])

# Fold vectors for the two overflow columns of the CIOS accumulator:
# parallel carry passes move carries UP into columns 30/31 and never
# back down, so the final normalization folds their content into the
# low 30 limbs mod q.  Weights: col 30 = 2^390, its >>13 half and
# col 31 = 2^403, col 31's >>13 half = 2^416.
_C390 = _int_to_limbs((1 << 390) % Q)
_C403 = _int_to_limbs((1 << 403) % Q)
_C416 = _int_to_limbs((1 << 416) % Q)


def to_mont_limbs(x: int) -> np.ndarray:
    """Host: integer mod q -> Montgomery-form limb vector."""
    return _int_to_limbs((x % Q) * R_MONT % Q)


# R^2 mod q in limb form: mont_mul(a_plain, R2) = REDC(a * R^2) = a*R,
# i.e. one device multiply converts a PLAIN limb vector to Montgomery
# form — the hook that lets staging ship raw byte-split limbs (ISSUE 5)
R2_LIMBS = _int_to_limbs(RADIX * RADIX % Q)

_BLS_LIMB_WEIGHTS = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(
    np.int32
)


def ints_to_limbs_batch(vals: list[int]) -> np.ndarray:
    """[n] integers mod q -> [n, NLIMBS] PLAIN (non-Montgomery) limb
    rows, vectorized: one bytes join + bit-matrix split replaces n
    Python bignum multiplies (the old per-point ``to_mont_limbs`` loop
    held the GIL for the whole staging pass)."""
    n = len(vals)
    rows = np.frombuffer(
        b"".join(v.to_bytes(48, "big") for v in vals), np.uint8
    ).reshape(n, 48)
    bits = np.unpackbits(rows[:, ::-1], axis=1, bitorder="little")
    bits = np.pad(bits, [(0, 0), (0, NLIMBS * LIMB_BITS - 384)])
    groups = bits.reshape(n, NLIMBS, LIMB_BITS).astype(np.int32)
    return groups @ _BLS_LIMB_WEIGHTS


def from_mont_int(limbs) -> int:
    """Host: loose Montgomery-form limbs -> canonical integer mod q."""
    return limbs_to_int(limbs) * pow(R_MONT, -1, Q) % Q


def _pass(t):
    """One parallel carry pass.  The TOP limb accumulates its incoming
    carry unmasked (values stay < 2^390-ish; masking would drop bits),
    growing by a few units per pass — harmless for int32."""
    r = jnp.concatenate([t[..., :-1] & MASK, t[..., -1:]], axis=-1)
    c = t[..., :-1] >> LIMB_BITS
    pad_cfg = [(0, 0)] * (t.ndim - 1)
    return r + jnp.pad(c, pad_cfg + [(1, 0)])[..., : t.shape[-1]]


def mont_mul(a, b):
    """Batched Montgomery product of signed-loose inputs (|value| < ~60q,
    |limb| < 2^13.1 — see the module docstring's magnitude audit).
    Output magnitude < 3.2q, loose limbs.  a, b: int32 [..., NLIMBS]."""
    pad_cfg = [(0, 0)] * (a.ndim - 1)
    b_ext = jnp.pad(b, pad_cfg + [(0, NCOLS - NLIMBS)])
    q_ext = jnp.asarray(Q_EXT)
    mu = jnp.int32(MU)
    t = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]) + (NCOLS,), jnp.int32)

    for i in range(NLIMBS):
        t = t + a[..., i : i + 1] * b_ext
        m = ((t[..., :1] & MASK) * mu) & MASK
        t = t + m * q_ext
        # t0 is now ≡ 0 mod 2^13; propagate its exact carry and shift
        # the limb window down one position
        carry0 = t[..., :1] >> LIMB_BITS
        t = jnp.concatenate(
            [t[..., 1:2] + carry0, t[..., 2:], jnp.zeros_like(t[..., :1])],
            axis=-1,
        )
        if (i % _CARRY_EVERY) == _CARRY_EVERY - 1:
            t = _pass(t)

    t = _pass(_pass(t))
    # fold the overflow columns (carry residue parked above limb 29 by
    # the upward-only passes) back into the 30-limb window mod q —
    # dropping them loses k*2^390 ≡ k*R, i.e. an off-by-k in the value
    # domain.  Signed split keeps every product < 2^26.
    c30 = t[..., NLIMBS : NLIMBS + 1]
    c31 = t[..., NLIMBS + 1 : NLIMBS + 2]
    lo30, hi30 = c30 & MASK, c30 >> LIMB_BITS
    lo31, hi31 = c31 & MASK, c31 >> LIMB_BITS
    head = (
        t[..., :NLIMBS]
        + lo30 * jnp.asarray(_C390)
        + (hi30 + lo31) * jnp.asarray(_C403)
        + hi31 * jnp.asarray(_C416)
    )
    return _pass(_pass(head))


def madd(a, b):
    return _pass(a + b)


def msub(a, b):
    # signed-loose: negative limbs/values are fine (see module docstring)
    return _pass(a - b)


def mul_small(a, k: int):
    """Multiply by a small non-negative integer constant (k <= 16:
    loose limbs * 16 < 2^18, one pass restores looseness).  Montgomery
    form is linear, so plain integer scaling stays in-form."""
    return _pass(a * jnp.int32(k))


# ---- complete projective G1 (Renes-Costello-Batina 2015, Alg. 7) -----------
# Point = (X, Y, Z) loose Montgomery limb arrays; identity = (0 : 1 : 0).


def point_add(p, q):
    """Complete addition: valid for every pair of subgroup points,
    including P == Q, P == -Q, and either operand at infinity."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = mont_mul(x1, x2)
    t1 = mont_mul(y1, y2)
    t2 = mont_mul(z1, z2)
    t3 = mont_mul(madd(x1, y1), madd(x2, y2))
    t3 = msub(t3, madd(t0, t1))
    t4 = mont_mul(madd(y1, z1), madd(y2, z2))
    t4 = msub(t4, madd(t1, t2))
    x3 = mont_mul(madd(x1, z1), madd(x2, z2))
    y3 = msub(x3, madd(t0, t2))
    x3 = madd(t0, t0)
    t0 = madd(x3, t0)
    t2 = mul_small(t2, B3)
    z3 = madd(t1, t2)
    t1 = msub(t1, t2)
    y3 = mul_small(y3, B3)
    x3 = mont_mul(t4, y3)
    t2 = mont_mul(t3, t1)
    x3 = msub(t2, x3)
    y3 = mont_mul(y3, t0)
    t1 = mont_mul(t1, z3)
    y3 = madd(t1, y3)
    t0 = mont_mul(t0, t3)
    z3 = mont_mul(z3, t4)
    z3 = madd(z3, t0)
    return (x3, y3, z3)


def _tree_reduce(p):
    while p[0].shape[0] > 1:
        half = p[0].shape[0] // 2
        p = point_add(tuple(c[:half] for c in p), tuple(c[half:] for c in p))
    return p


def _aggregate_impl(xs, ys, zs):
    """Tree-reduce a [B, NLIMBS] batch of projective points to one point.
    B must be a power of two (callers pad with the identity)."""
    return tuple(c[0] for c in _tree_reduce((xs, ys, zs)))


def _aggregate_plain_impl(xs, ys, zs):
    """Same contract as ``_aggregate_impl`` but over PLAIN limb rows:
    the Montgomery conversion (one mont_mul by R^2 per coordinate) rides
    inside the same dispatch, so the host stages raw byte-split limbs
    and never does per-point bignum arithmetic (ISSUE 5).  Identity pads
    are plain (0 : 1 : 0).  mont_mul output is < 3.2q loose — well
    inside the < ~60q input bound of the point-add tree."""
    r2 = jnp.broadcast_to(jnp.asarray(R2_LIMBS), xs.shape)
    return tuple(
        c[0]
        for c in _tree_reduce(tuple(mont_mul(c, r2) for c in (xs, ys, zs)))
    )


_aggregate_kernel = partial(jax.jit, static_argnames=())(_aggregate_impl)
_aggregate_plain_kernel = partial(jax.jit, static_argnames=())(
    _aggregate_plain_impl
)
# Donated variant (ISSUE 6, mirroring tpu/ed25519.py): the limb rows
# are per-wave staging temporaries, so donating them lets XLA recycle
# their device allocations across aggregation waves.
_aggregate_plain_kernel_donated = jax.jit(
    _aggregate_plain_impl, donate_argnums=(0, 1, 2)
)

_DONATE: bool | None = None


def _donate_buffers() -> bool:
    """Same gate as ed25519.BatchVerifier.donate_buffers: accelerator
    backends by default, HOTSTUFF_DONATE=1/0 forces either way."""
    global _DONATE
    if _DONATE is None:
        import os

        env = os.environ.get("HOTSTUFF_DONATE", "").strip().lower()
        if env:
            _DONATE = env not in ("0", "off", "no", "false")
        else:
            _DONATE = jax.default_backend() in ("tpu", "gpu")
    return _DONATE


def make_sharded_g1_aggregate(mesh):
    """Cross-device G1 aggregation (docs/BLS_TPU_DESIGN.md step 4):
    the batch axis is sharded over the mesh's ``dp`` axis; each device
    tree-reduces its slice to ONE partial point, the D partials cross
    the interconnect with ``all_gather`` (D x 90 int32 words — trivially
    small), and a log2(D)-deep tree replicated on every device combines
    them.  Point addition is not componentwise, so a plain ``psum``
    cannot apply — this is the psum-SHAPED reduction the design doc
    describes.  Batch must be a multiple of mesh size with a
    power-of-two per-device slice; the driver pads with identities."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DP_AXIS as axis, shard_map

    def local(xs, ys, zs):
        part = _tree_reduce((xs, ys, zs))  # [1, NLIMBS] per device
        gathered = tuple(
            jax.lax.all_gather(c[0], axis, axis=0, tiled=False)
            for c in part
        )  # [D, NLIMBS] replicated
        out = _tree_reduce(gathered)
        return out  # [1, NLIMBS] replicated

    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P()),
        # the all_gather DOES replicate the partials, but the static
        # varying-mesh-axes inference cannot see through the point-add
        # tree that follows — disable the check rather than fight it
        check_vma=False,
    )
    return jax.jit(fn)


# ---- host driver ------------------------------------------------------------


class TpuG1Aggregator:
    """Aggregate G1 points (vote signatures) on device.

    The device does the O(n) part (the point sum); the caller feeds the
    resulting aggregate into the host pairing check — one constant-cost
    pairing per QC regardless of committee size (docs/BLS_TPU_DESIGN.md).

    ``mesh`` (optional, a 1-D ``jax.sharding.Mesh`` over axis "dp")
    shards the batch across devices: per-device tree reduction, one
    all_gather of D partial points, replicated final tree — the
    multi-chip path, exercised on the 8-device CPU mesh in tests.

    Inputs must be subgroup points (the CPU deserialization layer
    checks, per-signature or once on the aggregate; completeness of the
    addition formula depends on it)."""

    PAD_SIZES = (8, 32, 128, 512)

    def __init__(self, mesh=None):
        self.mesh = mesh
        if mesh is not None:
            # fail at construction (node boot), not inside the first
            # QC verify: slices must be equal powers of two per device,
            # and the shard axis name is part of the kernel contract
            d = int(mesh.devices.size)
            if d & (d - 1):
                raise ValueError(
                    f"sharded G1 aggregation needs a power-of-two mesh, "
                    f"got {d} devices"
                )
            from ..parallel.mesh import DP_AXIS

            if tuple(mesh.axis_names) != (DP_AXIS,):
                raise ValueError(
                    f"sharded G1 aggregation needs a 1-D ('{DP_AXIS}',) "
                    f"mesh, got axes {tuple(mesh.axis_names)}"
                )
        self._sharded = (
            None if mesh is None else make_sharded_g1_aggregate(mesh)
        )

    def _padded_size(self, n: int) -> int:
        padded = next(
            (s for s in self.PAD_SIZES if s >= n),
            1 << (n - 1).bit_length(),
        )
        if self.mesh is not None:
            # equal power-of-two slices per device (mesh size validated
            # as a power of two in __init__, so this terminates)
            d = int(self.mesh.devices.size)
            while padded % d or (padded // d) & (padded // d - 1):
                padded *= 2
        return padded

    def aggregate(self, points: list[G1Point]) -> G1Point:
        real = [pt for pt in points if not pt.inf]
        if not real:
            return G1Point.identity()
        with _spans.span("prepare"):
            padded = self._padded_size(len(real))
            m = len(real)
            xs = np.zeros((padded, NLIMBS), np.int32)
            ys = np.zeros((padded, NLIMBS), np.int32)
            zs = np.zeros((padded, NLIMBS), np.int32)
            if self._sharded is None:
                # vectorized staging (ISSUE 5): ship PLAIN byte-split
                # limbs; the kernel Montgomery-converts on device, so
                # prepare does no per-point bignum arithmetic.  Real
                # rows are (x : y : 1) plain, identity pads (0 : 1 : 0)
                # plain — both mont-convert correctly in-kernel.
                xs[:m] = ints_to_limbs_batch([pt.x for pt in real])
                ys[:m] = ints_to_limbs_batch([pt.y for pt in real])
                zs[:m, 0] = 1
                ys[m:, 0] = 1
                kernel = (
                    _aggregate_plain_kernel_donated
                    if _donate_buffers()
                    else _aggregate_plain_kernel
                )
            else:
                # sharded path: the shard_map kernel's contract is
                # Montgomery-form rows — keep the host conversion
                one = to_mont_limbs(1)
                for i, pt in enumerate(real):
                    xs[i] = to_mont_limbs(pt.x)
                    ys[i] = to_mont_limbs(pt.y)
                    zs[i] = one
                for i in range(m, padded):
                    ys[i] = one  # identity rows: (0 : 1 : 0)
                kernel = self._sharded
        rec = _spans.recorder()
        if rec is None:
            x, y, z = kernel(jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs))
            # same fence as the profiled path (ISSUE 5): the dispatch
            # pipeline parks this worker thread here with the GIL
            # released while the next wave stages — the profiler
            # measures exactly what production runs
            x, y, z = jax.block_until_ready((x, y, z))
        else:
            # profiling: split the dispatch into its waterfall stages;
            # structurally identical to the production path above
            with rec.span("dispatch"):
                x, y, z = kernel(
                    jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs)
                )
            with rec.span("device.execute"):
                x, y, z = jax.block_until_ready((x, y, z))
        with _spans.span("readback"):
            return self._projective_to_affine(
                np.asarray(x).reshape(NLIMBS),
                np.asarray(y).reshape(NLIMBS),
                np.asarray(z).reshape(NLIMBS),
            )

    @staticmethod
    def _projective_to_affine(x, y, z) -> G1Point:
        zi = from_mont_int(z)
        if zi == 0:
            return G1Point.identity()
        xi = from_mont_int(x)
        yi = from_mont_int(y)
        z_inv = pow(zi, Q - 2, Q)
        return G1Point(xi * z_inv % Q, yi * z_inv % Q)


def _running_add_impl(ax, ay, az, px, py, pz):
    """One incremental accumulate (ISSUE 9): the new point arrives as
    PLAIN [1, NLIMBS] limb rows (byte-split on host, no bignum work),
    Montgomery-converts in-kernel (one R^2 multiply per coordinate,
    same trick as ``_aggregate_plain_impl``), then ``point_add``s into
    the Montgomery-form accumulator.  The result is ``_freshen``ed:
    unlike the log-depth aggregation tree, this chain is as deep as the
    committee (up to 512 sequential adds), and unfreshened point_add
    outputs compound ~x2.5 per round until the CIOS columns overflow
    int32 (see ``_freshen``'s magnitude audit)."""
    r2 = jnp.broadcast_to(jnp.asarray(R2_LIMBS), px.shape)
    p = tuple(mont_mul(c, r2) for c in (px, py, pz))
    out = point_add((ax, ay, az), p)
    return tuple(_freshen(c) for c in out)


_running_add_kernel = jax.jit(_running_add_impl)
# donated variant: the previous accumulator is dead the moment the new
# one exists — let XLA recycle its buffers across votes
_running_add_kernel_donated = jax.jit(
    _running_add_impl, donate_argnums=(0, 1, 2)
)


class TpuG1RunningSum:
    """Device-resident incremental G1 accumulator (ISSUE 9).

    ``TpuG1Aggregator`` batches the whole vote set at quorum;
    this keeps a running Σ sig_i ON DEVICE as votes arrive — one
    fixed-shape [1, NLIMBS] ``point_add`` dispatch per vote — so QC
    formation at quorum is a readback of an already-computed point:
    O(1) marginal work per vote, O(1) work at quorum.  The async
    dispatch never blocks the caller; only ``snapshot()`` fences.

    Same trust contract as the batch aggregator: callers feed subgroup
    points (completeness of the addition law depends on it)."""

    def __init__(self):
        self._acc = None
        self._count = 0
        self.reset()

    def reset(self) -> None:
        # identity (0 : 1 : 0) in Montgomery form
        self._acc = (
            jnp.zeros((1, NLIMBS), jnp.int32),
            jnp.asarray(to_mont_limbs(1), jnp.int32).reshape(1, NLIMBS),
            jnp.zeros((1, NLIMBS), jnp.int32),
        )
        self._count = 0

    def __len__(self) -> int:
        return self._count

    def add(self, pt: G1Point) -> None:
        """Accumulate one point; returns immediately (async dispatch)."""
        if pt.inf:
            return
        with _spans.span("agg.accumulate"):
            xs = jnp.asarray(ints_to_limbs_batch([pt.x]))
            ys = jnp.asarray(ints_to_limbs_batch([pt.y]))
            zs = np.zeros((1, NLIMBS), np.int32)
            zs[0, 0] = 1
            kernel = (
                _running_add_kernel_donated
                if _donate_buffers()
                else _running_add_kernel
            )
            self._acc = kernel(*self._acc, xs, ys, jnp.asarray(zs))
            self._count += 1

    def snapshot(self) -> G1Point:
        """Fence the pending adds and read the aggregate back (affine)."""
        with _spans.span("agg.snapshot"):
            x, y, z = jax.block_until_ready(self._acc)
            return TpuG1Aggregator._projective_to_affine(
                np.asarray(x).reshape(NLIMBS),
                np.asarray(y).reshape(NLIMBS),
                np.asarray(z).reshape(NLIMBS),
            )


# ---- batched variable-base scalar multiplication ----------------------------
# The per-entry G1 work of distinct-digest TC verification (VERDICT r5
# item 8): r_i·H(m_i) for every entry plus the Σ r_i·sig_i aggregate.
# One MSB-first double-and-add ladder, BRANCHLESS (the conditional add
# is a jnp.where select — complete addition makes the "add" leg valid
# even when it is discarded), vectorized over the batch.  Cost:
# 2·NBITS point adds of [B]-wide batches; for 128-bit weights that is
# 256 adds regardless of batch size — the device eats the whole storm's
# ladder work in one dispatch.


SCALAR_BITS = 128  # random small-exponent weights (service.py)

_ONE_MONT = to_mont_limbs(1)


def _freshen(a):
    """Re-normalize a signed-loose value to magnitude < ~1.3q by one
    Montgomery multiply with the form of 1 (REDC divides by R, and
    R/q > 500 crushes any accumulated growth).  The SEQUENTIAL ladder
    needs this every iteration: point_add's per-op outputs can reach
    ~10q, and feeding them straight back in compounds (~x2.5 per
    round) until the CIOS columns overflow int32 — measured as wrong
    results after ~40-50 chained doublings.  The aggregation tree
    (log-depth, fresh 1.5q leaves) never chains deep enough to need it."""
    one = jnp.broadcast_to(jnp.asarray(_ONE_MONT), a.shape).astype(jnp.int32)
    return mont_mul(a, one)


@partial(jax.jit, static_argnames=("nbits",))
def _scalar_mult_kernel(bits, xs, ys, zs, nbits: int = SCALAR_BITS):
    """bits: [nbits, B] int32 (MSB first); points [B, NLIMBS] loose
    Montgomery projective.  Returns k_i·P_i, [B, NLIMBS] each."""
    b = xs.shape[0]
    acc = (
        jnp.zeros((b, NLIMBS), jnp.int32),
        jnp.broadcast_to(jnp.asarray(to_mont_limbs(1)), (b, NLIMBS)).astype(
            jnp.int32
        ),
        jnp.zeros((b, NLIMBS), jnp.int32),
    )

    def body(i, acc):
        acc = point_add(acc, acc)
        added = point_add(acc, (xs, ys, zs))
        take = bits[i][:, None] != 0
        acc = tuple(
            jnp.where(take, ad, ac) for ac, ad in zip(acc, added)
        )
        return tuple(_freshen(c) for c in acc)

    return jax.lax.fori_loop(0, nbits, body, acc)


class TpuG1ScalarMul:
    """Batched k_i·P_i on device (the TC-storm per-entry ladders).

    The host packs scalars into MSB-first bit planes and points into
    Montgomery limbs; the device runs one branchless ladder over the
    whole batch; affine conversion happens on the host (one modular
    inversion per point, ~30 us — noise next to the ladder).
    """

    PAD_SIZES = (8, 32, 128, 512)

    def __init__(self, nbits: int = SCALAR_BITS):
        self.nbits = nbits

    def _padded(self, n: int) -> int:
        return next(
            (s for s in self.PAD_SIZES if s >= n), 1 << (n - 1).bit_length()
        )

    def mul_arrays(self, scalars: list[int], points: list[G1Point]):
        """Device ladder; returns the raw projective result as DEVICE
        arrays (x, y, z of shape [padded, NLIMBS]) — callers chain
        further device work (the storm offload feeds the wsig segment
        straight into the aggregation kernel) or convert on host."""
        n = len(points)
        assert len(scalars) == n and n > 0
        padded = self._padded(n)
        nbytes = (self.nbits + 7) // 8
        sbytes = np.zeros((padded, nbytes), np.uint8)
        packed = b"".join(k.to_bytes(nbytes, "little") for k in scalars)
        sbytes[:n] = np.frombuffer(packed, np.uint8).reshape(n, nbytes)
        lsb_first = np.unpackbits(sbytes, axis=1, bitorder="little")
        # MSB-first planes: [nbits, padded]
        bits = lsb_first[:, : self.nbits][:, ::-1].T.astype(np.int32)
        xs = np.zeros((padded, NLIMBS), np.int32)
        ys = np.zeros((padded, NLIMBS), np.int32)
        zs = np.zeros((padded, NLIMBS), np.int32)
        one = to_mont_limbs(1)
        ys[:] = one  # identity rows by default (0 : 1 : 0)
        for i, pt in enumerate(points):
            if not pt.inf:
                xs[i] = to_mont_limbs(pt.x)
                ys[i] = to_mont_limbs(pt.y)
                zs[i] = one
        return _scalar_mult_kernel(
            jnp.asarray(np.ascontiguousarray(bits)),
            jnp.asarray(xs),
            jnp.asarray(ys),
            jnp.asarray(zs),
            nbits=self.nbits,
        )

    def mul(
        self, scalars: list[int], points: list[G1Point]
    ) -> list[G1Point]:
        """[k_i·P_i] — scalars must fit in ``nbits``."""
        if not points:
            return []
        for k in scalars:
            assert 0 <= k < (1 << self.nbits)
        x, y, z = (np.asarray(a) for a in self.mul_arrays(scalars, points))
        return [
            TpuG1Aggregator._projective_to_affine(x[i], y[i], z[i])
            for i in range(len(points))
        ]


# ---- distinct-digest storm offload ------------------------------------------
# The device side of VERDICT r5 item 8: for an all-distinct TC batch,
# every per-entry G1 ladder — signature subgroup check (order·sig),
# weighted signature (r_i·sig_i), and weighted cofactor-cleared hash
# ((r_i·h_eff)·H_base(m_i)) — runs as ONE batched device ladder of
# 3n points, followed by an on-device aggregation of the weighted
# signatures.  The host (native/bls_pairing.cpp) keeps decompression,
# hashing, and the pairing product over the returned points.


class TpuStormOffload:
    """Batched G1 ladders for distinct-digest TC verification."""

    def __init__(self):
        self._mul = TpuG1ScalarMul(nbits=256)
        # compiled (ladder_pad, agg_pad) shape pairs: batch_points
        # REFUSES un-warmed shapes (shape_ready) so a differently-sized
        # storm can never trigger a cold jit compile mid-consensus —
        # the caller falls back to the host route instead
        self._warm_shapes: set[tuple[int, int]] = set()
        self.ready = False

    def _shapes_for(self, n: int) -> tuple[int, int]:
        return self._mul._padded(3 * n), 1 << max(0, (n - 1).bit_length())

    def shape_ready(self, n: int) -> bool:
        return self._shapes_for(n) in self._warm_shapes

    def warmup(self, n: int = 171) -> None:
        """Compile/cache the ladder + aggregation shapes for an n-entry
        storm (3n-point ladder batch) before the consensus hot path.
        Call once per storm size of interest; other sizes fall back to
        the host route rather than compiling under a round timer."""
        from ..crypto.bls.curve import G1Point

        g = G1Point.generator()
        ladder_pad, agg_pad = self._shapes_for(n)
        self._mul.mul([1] * ladder_pad, [g] * ladder_pad)  # the storm shape
        # aggregation shape for the wsig segment
        xs = np.zeros((agg_pad, NLIMBS), np.int32)
        ys = np.tile(to_mont_limbs(1), (agg_pad, 1)).astype(np.int32)
        zs = np.zeros((agg_pad, NLIMBS), np.int32)
        _aggregate_kernel(jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs))
        self._warm_shapes.add((ladder_pad, agg_pad))
        self.ready = True

    def batch_points(self, weights: list[int], bases, sigs):
        """(whm_points, agg_point, subgroup_ok) for the native pairing
        product.  ``bases`` are PRE-cofactor hash points, ``sigs``
        on-curve (subgroup membership checked HERE via the order
        ladder).  whm_i = (w_i·h_eff)·base_i; agg = Σ w_i·sig_i."""
        from ..crypto.bls.curve import H1
        from ..crypto.bls.fields import R as ORDER

        n = len(bases)
        assert len(weights) == n and len(sigs) == n
        scalars = (
            [w * H1 for w in weights] + list(weights) + [ORDER] * n
        )
        x, y, z = self._mul.mul_arrays(scalars, list(bases) + list(sigs) * 2)
        x, y, z = np.asarray(x), np.asarray(y), np.asarray(z)
        # subgroup: order·sig must be the identity (z == 0 mod q).
        # The G1 cofactor has SMALL prime factors, so a small-order
        # component must be caught per signature — aggregate-only
        # checking is unsound here (see native verify_batch's comment).
        subgroup_ok = all(
            from_mont_int(z[2 * n + i]) == 0 for i in range(n)
        )
        whm = [
            TpuG1Aggregator._projective_to_affine(x[i], y[i], z[i])
            for i in range(n)
        ]
        # aggregate the wsig segment on device; the pad MUST come from
        # _shapes_for — the shape_ready gate compares against it, and an
        # independently computed pad could drift and defeat the
        # no-cold-compile-mid-consensus guarantee
        _, agg_pad = self._shapes_for(n)
        xs = np.zeros((agg_pad, NLIMBS), np.int32)
        ys = np.tile(to_mont_limbs(1), (agg_pad, 1)).astype(np.int32)
        zs = np.zeros((agg_pad, NLIMBS), np.int32)
        xs[:n], ys[:n], zs[:n] = x[n : 2 * n], y[n : 2 * n], z[n : 2 * n]
        ax, ay, az = _aggregate_kernel(
            jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(zs)
        )
        agg = TpuG1Aggregator._projective_to_affine(
            np.asarray(ax).reshape(NLIMBS),
            np.asarray(ay).reshape(NLIMBS),
            np.asarray(az).reshape(NLIMBS),
        )
        return whm, agg, subgroup_ok
