"""GF(2^255-19) arithmetic in JAX int32 limbs — the TPU field layer.

Design (TPU-first, see SURVEY.md §7 "hard parts"): field elements are 20
little-endian limbs of 13 bits held in int32. 13-bit limbs are chosen so a
schoolbook product term is < 2^26 and a 20-term accumulation stays < 2^31,
i.e. everything fits native int32 multiply-accumulate on the TPU VPU — no
int64 emulation, no float tricks. All ops are shape-static and jit/vmap
friendly; the trailing axis is always the limb axis.

Reduction is fully data-parallel: instead of a sequential carry chain
(whose ~39-step dependency chain would serialize the VPU), ``carry`` runs
a constant number of parallel carry passes — every limb computes its
carry simultaneously and receives its neighbour's; carries shrink
geometrically, so FOUR passes reach the loose bound from any product- or
sum-scale input (bound analysis in ``carry``'s docstring).

Representation invariant ("loose normalized", the output of ``carry``):
limbs[1..18] <= 2^13, limb 19 <= 256, limb 0 <= 2^13 + 608. (Bounds are
inclusive — parallel passes can leave a limb at exactly 2^13.)
``canonical`` produces the unique fully-reduced representation (used for
equality / parity / encoding).

Correctness oracle: ``hotstuff_tpu.crypto.ed25519_ref`` (arbitrary-precision
ints), tested in tests/test_tpu_field.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

NLIMBS = 20
LIMB_BITS = 13
MASK = (1 << LIMB_BITS) - 1

P_INT = 2**255 - 19

# 2^260 = 2^5 * 2^255 ≡ 19 * 32 (mod p): fold multiplier for limb index 20+j.
FOLD = 19 * 32  # 608
# 2^255 ≡ 19: fold multiplier for bits >= 255 (bit 8 of limb 19).
TOP_FOLD = 19
TOP_SHIFT = 255 - 19 * LIMB_BITS  # = 8
TOP_MASK = (1 << TOP_SHIFT) - 1

# Loose-normalized inclusive limb bounds (see carry()).
_B0 = (1 << LIMB_BITS) + FOLD  # limb 0
_BJ = 1 << LIMB_BITS  # limbs 1..18
_B19 = 1 << TOP_SHIFT  # limb 19


def limbs_from_int(x: int) -> np.ndarray:
    """Host-side: Python int -> canonical limb vector (numpy int32)."""
    x %= P_INT
    out = np.zeros(NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= LIMB_BITS
    return out


def int_from_limbs(limbs) -> int:
    """Host-side: limb vector -> Python int (not reduced mod p)."""
    arr = np.asarray(limbs)
    return sum(int(v) << (LIMB_BITS * i) for i, v in enumerate(arr.tolist()))


def _sub_pad() -> np.ndarray:
    """8p decomposed so each limb strictly dominates any loose-normalized
    operand limb (borrow-adjusted): c[0] >= B0, c[1..18] >= 2^13,
    c[19] >= 256 — so a + (PAD - b) is non-negative limb-wise."""
    n = [(8 * P_INT >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)]
    c = list(n)
    c[0] = n[0] + (1 << LIMB_BITS)
    for j in range(1, NLIMBS - 1):
        c[j] = n[j] - 1 + (1 << LIMB_BITS)
    c[NLIMBS - 1] = n[NLIMBS - 1] - 1
    assert sum(v << (LIMB_BITS * i) for i, v in enumerate(c)) == 8 * P_INT
    assert c[0] >= _B0 and all(v >= _BJ for v in c[1:-1]) and c[-1] >= _B19
    return np.array(c, dtype=np.int32)


SUB_PAD = _sub_pad()
# p itself in limbs (limbs_from_int reduces mod p, so build directly).
P_LIMBS = np.array(
    [(P_INT >> (LIMB_BITS * i)) & MASK for i in range(NLIMBS)], dtype=np.int32
)


def _fold39(z):
    """Fold product columns 20..38 (weight 608 * 2^13j) into columns 0..19.

    High columns are split into 13-bit halves first so every fold term
    stays within int32: col20+j = h; h = h_lo + 2^13 h_hi contributes
    608*h_lo at limb j and 608*h_hi at limb j+1.
    """
    lo = z[..., :NLIMBS]
    hi = z[..., NLIMBS:]
    hi_lo = (hi & MASK) * FOLD
    hi_hi = (hi >> LIMB_BITS) * FOLD
    pad_cfg = [(0, 0)] * (z.ndim - 1)
    add0 = jnp.pad(hi_lo, pad_cfg + [(0, NLIMBS - hi.shape[-1])])
    add1 = jnp.pad(hi_hi, pad_cfg + [(1, NLIMBS - hi.shape[-1] - 1)])
    return lo + add0 + add1


def _ppass(z):
    """One parallel carry pass over 20 columns: every limb emits its
    carry simultaneously; bit >= 2^13 moves one limb up, bits >= 255
    (limb 19, bit 8+) fold to limb 0 with x19."""
    r = jnp.concatenate(
        [z[..., : NLIMBS - 1] & MASK, z[..., NLIMBS - 1 :] & TOP_MASK], axis=-1
    )
    c = z[..., : NLIMBS - 1] >> LIMB_BITS
    c_top = (z[..., NLIMBS - 1 :] >> TOP_SHIFT) * TOP_FOLD
    return jnp.concatenate(
        [r[..., :1] + c_top, r[..., 1:] + c], axis=-1
    )


def carry(z, passes: int = 4):
    """Reduce any bounded non-negative limb vector (a 39-column product or
    a 20-column sum) to loose-normalized 20-limb form.

    Convergence (inputs non-negative, columns < 2^31):
    after fold, columns < ~1.91e9; pass 1 leaves limbs <= 8191 + 233k
    (limb 0 <= 8191 + 1.4e8); pass 2 <= ~26k (limb 1 inherits limb 0's
    large carry, so THREE passes do NOT suffice — a host search finds
    product-scale inputs leaving a limb at 8193 after 3 passes);
    pass 3 <= ~8.8k; pass 4 reaches limb0 <= 2^13+608,
    limbs[1..18] <= 2^13, limb19 <= 256.  Every pass is a handful of
    full-width vector ops — no sequential carry chain.

    ``passes`` may be lowered by callers whose inputs are tighter than
    the worst case.  For sums/differences of loose-normalized values
    (columns < 2^14.7) TWO passes reach the invariant: pass 1 leaves
    limbs <= 8191 + 3 (limb 0 <= 8191 + 152, limb 19 <= 258), pass 2
    absorbs the stragglers (limb 0 <= 8191 + 19, limbs <= 8192,
    limb 19 <= 256) — bounds tested exhaustively at the extremes in
    tests/test_tpu_field.py.
    """
    if z.shape[-1] > NLIMBS:
        z = _fold39(z)
    for _ in range(passes):
        z = _ppass(z)
    return z


def add(a, b):
    return carry(a + b, passes=2)


def sub(a, b):
    # a - b + 8p keeps every limb non-negative before the carry passes.
    return carry(a + (jnp.asarray(SUB_PAD) - b), passes=2)


# prod[k] = sum_{i+j=k} a_i b_j.  The anti-diagonal collapse rides the
# MXU as a dense matmul against a constant one-hot matrix W[400, 39]
# instead of a VPU scatter-add: slope-timed on the real chip (r2,
# exp notes) the scatter mul costs ~10 us per 1024-batch mul and the
# matmul form ~4 us — elementwise/scatter ops are HBM-bound while the
# MXU does the 39-way reduction essentially for free.
#
# Exactness: outer products are < (2^13+608)^2 = 7.75e7, so each is
# split into a 13-bit lo and a hi half < 7.75e7/2^13 = 9460; column sums
# over <= 20 terms stay < 2^19 — exact in f32 (24-bit mantissa) even
# before f32-HIGHEST forces full-precision MXU passes.  Recombined in
# int32: max = 20*9460*2^13 + 20*(2^13-1) = 1.55e9 < 2^31.
_DIAG_IDX = np.add.outer(np.arange(NLIMBS), np.arange(NLIMBS))  # [20,20]


def _conv_weights() -> np.ndarray:
    w = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS - 1), np.float32)
    w[np.arange(NLIMBS * NLIMBS), _DIAG_IDX.reshape(-1)] = 1.0
    return w


W_CONV = _conv_weights()


def mul(a, b):
    """Schoolbook polynomial multiply + reduction. a, b loose normalized."""
    outer = a[..., :, None] * b[..., None, :]  # [..., 20, 20] int32-safe
    outer = outer.reshape(a.shape[:-1] + (NLIMBS * NLIMBS,))
    lo = (outer & MASK).astype(jnp.float32)
    hi = (outer >> LIMB_BITS).astype(jnp.float32)
    w = jnp.asarray(W_CONV)
    slo = jnp.dot(lo, w, precision=jax.lax.Precision.HIGHEST)
    shi = jnp.dot(hi, w, precision=jax.lax.Precision.HIGHEST)
    prod = slo.astype(jnp.int32) + (shi.astype(jnp.int32) << LIMB_BITS)
    return carry(prod)


def mul_small(a, k: int):
    """Multiply by a small non-negative constant (k < 2^17).  k <= 4
    keeps columns < 2^15.7, within the 2-pass carry regime."""
    return carry(a * jnp.int32(k), passes=2 if k <= 4 else 4)


# Squaring uses the symmetric half of the product: prod[k] =
# sum_{i<j, i+j=k} 2 a_i a_j + [k even] a_{k/2}^2 — 210 upper-triangle
# products instead of 400, with the factor 2 folded into the collapse
# matrix.  Exactness: doubled hi-column sums stay < 2^20 (f32-exact) and
# the recombined value equals the full convolution, so the mul bound
# (1.55e9 < 2^31) carries over unchanged.
_TRI_I, _TRI_J = np.triu_indices(NLIMBS)


def _sqr_weights() -> np.ndarray:
    w = np.zeros((len(_TRI_I), 2 * NLIMBS - 1), np.float32)
    for t, (i, j) in enumerate(zip(_TRI_I, _TRI_J)):
        w[t, i + j] = 1.0 if i == j else 2.0
    return w


W_SQR = _sqr_weights()


def sqr(a):
    terms = a[..., _TRI_I] * a[..., _TRI_J]  # [..., 210] int32-safe
    lo = (terms & MASK).astype(jnp.float32)
    hi = (terms >> LIMB_BITS).astype(jnp.float32)
    w = jnp.asarray(W_SQR)
    slo = jnp.dot(lo, w, precision=jax.lax.Precision.HIGHEST)
    shi = jnp.dot(hi, w, precision=jax.lax.Precision.HIGHEST)
    prod = slo.astype(jnp.int32) + (shi.astype(jnp.int32) << LIMB_BITS)
    return carry(prod)


def _sqr_n(a, n: int):
    """n repeated squarings via fori_loop (body traced once — keeps the XLA
    graph compact; a fully unrolled inversion chain takes minutes to compile)."""
    return jax.lax.fori_loop(0, n, lambda _, t: sqr(t), a)


def pow_inv(a):
    """a^(p-2) = a^-1 via the standard curve25519 addition chain."""
    z2 = sqr(a)
    z9 = mul(sqr(sqr(z2)), a)
    z11 = mul(z9, z2)
    z2_5_0 = mul(sqr(z11), z9)
    z2_10_0 = mul(_sqr_n(z2_5_0, 5), z2_5_0)
    z2_20_0 = mul(_sqr_n(z2_10_0, 10), z2_10_0)
    z2_40_0 = mul(_sqr_n(z2_20_0, 20), z2_20_0)
    z2_50_0 = mul(_sqr_n(z2_40_0, 10), z2_10_0)
    z2_100_0 = mul(_sqr_n(z2_50_0, 50), z2_50_0)
    z2_200_0 = mul(_sqr_n(z2_100_0, 100), z2_100_0)
    z2_250_0 = mul(_sqr_n(z2_200_0, 50), z2_50_0)
    return mul(_sqr_n(z2_250_0, 5), z11)  # 2^255 - 21


def _chain(z):
    """One sequential signed carry pass (host-rare paths: canonical only).
    Returns (list of limb columns, final carry column)."""
    c = jnp.zeros_like(z[..., :1])
    outs = []
    for i in range(z.shape[-1]):
        x = z[..., i : i + 1] + c
        c = x >> LIMB_BITS  # arithmetic shift: floor semantics for negatives
        outs.append(x & MASK)
    return outs, c


def _strict(a):
    """Loose normalized -> strictly normalized (every limb < 2^13, value <
    2^255 + 19, unique up to one conditional p-subtraction)."""
    outs, _ = _chain(a)  # value < 2^260, carry out of limb 19 is 0
    z = jnp.concatenate(outs, axis=-1)
    for _ in range(2):  # peel bit 255 (at most twice: value < 2^256)
        top = z[..., NLIMBS - 1 :] >> TOP_SHIFT
        z = jnp.concatenate(
            [
                z[..., :1] + top * TOP_FOLD,
                z[..., 1 : NLIMBS - 1],
                z[..., NLIMBS - 1 :] - (top << TOP_SHIFT),
            ],
            axis=-1,
        )
        outs, _ = _chain(z)
        z = jnp.concatenate(outs, axis=-1)
    return z


def canonical(a):
    """Fully reduce loose-normalized limbs to the unique value in [0, p)."""
    a = _strict(a)
    p_limbs = jnp.asarray(P_LIMBS)
    for _ in range(2):
        borrow = jnp.zeros_like(a[..., :1])
        outs = []
        for i in range(NLIMBS):
            x = a[..., i : i + 1] - p_limbs[i] + borrow
            borrow = x >> LIMB_BITS
            outs.append(x & MASK)
        diff = jnp.concatenate(outs, axis=-1)
        a = jnp.where(borrow >= 0, diff, a)  # no final borrow -> a >= p
    return a


def eq(a, b):
    """Field equality of loose-normalized elements -> bool[...]."""
    return jnp.all(canonical(a) == canonical(b), axis=-1)


def is_odd(a):
    """Parity of the canonical value -> int32[...] in {0,1}."""
    return canonical(a)[..., 0] & 1
