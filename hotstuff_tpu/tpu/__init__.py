"""Device modules (JAX/XLA/Pallas kernels).

Importing this package activates the framework's persistent compilation
cache.  ``hotstuff_tpu.__init__`` exports the cache path via the
``JAX_COMPILATION_CACHE_DIR`` env var, but jax 0.9.0 does NOT read that
env var into ``jax_compilation_cache_dir`` (verified: the config stays
None and no cache file is ever written) — it must be set through
``jax.config.update``.  That silent miss cost minutes of Mosaic
recompilation of the Pallas verify kernel in EVERY process all round
("the cache does not cover the tunnel" in earlier notes was this bug:
measured here, a 4.8 s compile loads in under 2 s from a second process
once the config is actually set).
"""

import os as _os

import jax as _jax

# An explicitly EMPTY env var disables the cache (used by the driver
# dryrun, where tiny CPU compiles gain nothing and stale AOT entries
# could mismatch host machine features).
_cache_dir = _os.environ.get(
    "JAX_COMPILATION_CACHE_DIR",
    _os.path.expanduser("~/.cache/hotstuff_tpu/jax"),
)
if _cache_dir:
    _jax.config.update("jax_compilation_cache_dir", _cache_dir)
