"""Pallas TPU kernel for the fused double-scalar multiplication.

This is the VMEM-resident rewrite of ``curve.dual_scalar_mult`` — the
hot loop of batched Ed25519 verification (reference hot spot:
``Signature::verify_batch``, crypto/src/lib.rs:213-226).  The XLA
version is HBM-bound: every field op round-trips intermediates through
HBM, and slope-timing on hardware shows elementwise throughput pinned
at memory bandwidth.  Here the whole 32-macro-step Straus scan runs
inside ONE kernel with every intermediate in VMEM.

Layout: limb-major ``[NLIMBS, Bt]`` — the batch tile rides the 128-wide
lane dimension (full VPU utilization), limbs ride sublanes.  The
schoolbook-product collapse is an int32 diagonal sum on the VPU (see
_mul_t — it replaced the round-2 one-hot MXU matmul, whose ~2.5%-dense
weight matrix burned ~40x the useful MACs and dominated the kernel).
Per-batch table selects use a 4-level tournament of ``jnp.where``
(15 selects of a [4, 20, Bt] entry vs 16 one-hot multiply-adds).
Constant inputs (base-point table, curve constant, subtraction pad) are
kernel INPUTS — Pallas kernels cannot capture traced constants — mapped
to block (0, 0) so every grid tile reads the same copy.

The production kernel is FULLY fused (round 3): the Straus scan AND the
compressed-encoding comparison (Fermat inversion, canonicalization,
y/sign compare) run in one Pallas dispatch — the former XLA epilogue
was ~265 sequential HBM round-trips, ~2 ms of the 256-vote QC's device
time.  Correctness oracle: ``curve.dual_scalar_mult`` + 
``curve.compressed_equals`` (RFC-8032-vector-tested); parity is tested
in interpret mode on CPU and on device in tests/test_tpu_ed25519.py.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..crypto import ed25519_ref as ref
from . import curve, field as F

NL = F.NLIMBS  # 20
NCOLS = 2 * NL - 1  # 39
LANE_TILE = 128  # minimum batch tile (lane width)
# Batch tile.  128 (one lane width) since round 3: the kernel is
# VPU-THROUGHPUT-bound — slope-timing at 128/256/512 lanes measured
# 1.83/3.28/6.47 ms, ~linear in lanes (scripts/probe_tile_scaling.py) —
# so narrower tiles cost nothing, and the round-3 wave batching (which
# roughly triples per-tile transients: the mul waves materialize
# [NL, NL, 4*Bt] outer products) blows the 16M scoped-VMEM cap at 256
# lanes (21.7M, measured via scripts/probe_vmem_shapes.py).
BT = 128

# A 512-lane "wide tile" for the split kernel (one 16-step scan for a
# 256-signature QC) existed through round 2 and was DELETED in round 3:
# the same linear-in-lanes measurement shows a 512-lane 16-step scan
# can never beat two 256-lane tiles, and its Mosaic compile never
# finished (~58 min, aborted) anyway.


_HIGH = jax.lax.Precision.HIGHEST

# Host-side constants (numpy; shipped to the kernel as inputs).


def _bake_t2d(table: np.ndarray) -> np.ndarray:
    """Copy of a [n, 4, NL] base table with the T column premultiplied
    by the curve constant 2d.  Table points are only ever the ``q``
    operand of ``_point_add_t``, whose c-term is 2d*T1*T2 — baking 2d
    into T2 turns that into the single mul T1*T2d and removes one field
    mul from EVERY table addition in the scan."""
    out = table.copy()
    d2_int = 2 * ref.D % ref.P
    for m in range(out.shape[0]):
        x = F.int_from_limbs(out[m, 0])
        y = F.int_from_limbs(out[m, 1])
        out[m, 3] = F.limbs_from_int(x * y % ref.P * d2_int % ref.P)
    return out


_BTAB_T = (
    _bake_t2d(np.asarray(curve.B_TABLE8))  # [256, 4, 20], T -> T*2d
    .astype(np.float32)
    .reshape(1 << curve.B_WINDOW, 4 * NL)
    .T.copy()
)  # [80, 256]; limb values < 2^13+608 are f32-exact
_D2_COL = curve.D2_LIMBS.reshape(NL, 1)  # curve constant 2d, limb-major
_SUBPAD_COL = F.SUB_PAD.reshape(NL, 1)


class _Env:
    """Kernel-side handles to the constant inputs."""

    def __init__(self, btab, d2, subpad):
        self.btab = btab  # [80, 256] f32
        self.d2 = d2  # [NL, 1] int32
        self.subpad = subpad  # [NL, 1] int32


# ---- limb-major field ops (values, not refs; all [NL, Bt]) -----------------


def _carry_t(z, passes: int):
    """Parallel carry passes along axis -2 (the limb axis)."""
    if z.shape[-2] > NL:
        lo = z[..., :NL, :]
        hi = z[..., NL:, :]
        hi_lo = (hi & F.MASK) * F.FOLD
        hi_hi = (hi >> F.LIMB_BITS) * F.FOLD
        nhi = z.shape[-2] - NL
        pad = [(0, 0)] * (z.ndim - 2)
        add0 = jnp.pad(hi_lo, pad + [(0, NL - nhi), (0, 0)])
        add1 = jnp.pad(hi_hi, pad + [(1, NL - nhi - 1), (0, 0)])
        z = lo + add0 + add1
    for _ in range(passes):
        r = jnp.concatenate(
            [z[..., : NL - 1, :] & F.MASK, z[..., NL - 1 :, :] & F.TOP_MASK],
            axis=-2,
        )
        c = z[..., : NL - 1, :] >> F.LIMB_BITS
        c_top = (z[..., NL - 1 :, :] >> F.TOP_SHIFT) * F.TOP_FOLD
        z = jnp.concatenate([r[..., :1, :] + c_top, r[..., 1:, :] + c], axis=-2)
    return z


def _mul_t(env, a, b):
    """[NL, Bt] x [NL, Bt] -> [NL, Bt]; int32 diagonal collapse.

    The schoolbook product sum out[c] = sum_{i+j=c} a_i*b_j used to ride
    the MXU as a one-hot f32 matmul ([39,400]@[400,Bt], with the lo/hi
    13-bit split for f32 exactness).  That matrix is ~2.5% dense — each
    of the 400 products feeds exactly ONE output column — so the MXU
    burns ~40x the useful MACs, and at QC tile widths the two dots
    dominated the whole kernel.  The diagonal sum is 20 shifted int32
    adds on the VPU instead, with NO lo/hi split or f32 conversions:
    products are exact in int32 (limbs < 2^13+608 -> products < 2^26.3,
    20-term column sums < 2^30.6 < 2^31), and the value handed to
    _carry_t is bit-identical to what the matmul produced, so the carry
    bound analysis is unchanged."""
    outer = a[:, None, :] * b[None, :, :]  # [NL, NL, Bt]
    total = None
    for i in range(NL):
        shifted = jnp.pad(outer[i], [(i, NL - 1 - i), (0, 0)])  # [39, Bt]
        total = shifted if total is None else total + shifted
    return _carry_t(total, passes=4)


def _add_t(a, b):
    return _carry_t(a + b, passes=2)


def _sub_t(env, a, b):
    return _carry_t(a + (env.subpad - b), passes=2)


def _dbl_small_t(a):
    return _carry_t(a * jnp.int32(2), passes=2)


# ---- wave batching ----------------------------------------------------------
#
# At QC-shaped tiles ([NL, 128..512]) every field op is a handful of
# vregs, so the kernel is dominated by per-op issue overhead, not
# arithmetic.  The point formulas have natural 3-4-wide independent
# "waves" of muls (e.g. add-2008-hwcd-3's a, b, t1*t2, z1*z2); lane-
# concatenating a wave runs ONE outer product + ONE [39,400]@[400,n*Bt]
# MXU collapse + ONE carry chain over all of them, quadrupling the work
# per vector instruction at identical per-column math (the carry bound
# analysis is unchanged — columns never interact).


def _mul_wave_t(env, pairs):
    """len(pairs) independent [NL, Bt] products as one batched _mul_t."""
    if len(pairs) == 1:
        return [_mul_t(env, *pairs[0])]
    bt = pairs[0][0].shape[-1]
    a = jnp.concatenate([p[0] for p in pairs], axis=-1)
    b = jnp.concatenate([p[1] for p in pairs], axis=-1)
    prod = _mul_t(env, a, b)
    return [prod[..., i * bt : (i + 1) * bt] for i in range(len(pairs))]


def _lin_wave_t(terms, bt):
    """Batched 2-pass carry over pre-formed linear combinations.  Each
    term must be exactly one of the forms _add_t/_sub_t/_dbl_small_t
    carry today (x + y, x + (subpad - y), 2*x of carried values) so the
    2-pass bound argument applies column-by-column unchanged."""
    z = jnp.concatenate(terms, axis=-1)
    z = _carry_t(z, passes=2)
    return [z[..., i * bt : (i + 1) * bt] for i in range(len(terms))]


# ---- limb-major point ops: points are [4, NL, Bt] stacks (X, Y, Z, T) ------


def _point_add_t(env, p, q, need_t: bool = True):
    """Unified extended-coordinate addition (add-2008-hwcd-3), waved.

    ``p`` is an accumulator with a PLAIN T coordinate; ``q`` is a table
    point whose T is premultiplied by 2d (_bake_t2d / the in-kernel
    entry conversion), so the c-term is the single mul t1*t2d inside
    wave 1.

    ``need_t=False`` skips producing the T coordinate (one mul slot in
    wave 2): doublings ignore their input's T, so an addition feeding a
    doubling run — or the final scan output, which only X/Y/Z reach —
    never needs it.  The slot is zero-filled to keep the stack shape."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    x2, y2, z2, t2 = q[0], q[1], q[2], q[3]
    bt = x1.shape[-1]
    dm1, sm1, dm2, sm2 = _lin_wave_t(
        [
            y1 + (env.subpad - x1),
            y1 + x1,
            y2 + (env.subpad - x2),
            y2 + x2,
        ],
        bt,
    )
    a, b, c, zz = _mul_wave_t(
        env, [(dm1, dm2), (sm1, sm2), (t1, t2), (z1, z2)]
    )
    d = _dbl_small_t(zz)
    e, f, g, h = _lin_wave_t(
        [
            b + (env.subpad - a),
            d + (env.subpad - c),
            d + c,
            b + a,
        ],
        bt,
    )
    prods = _mul_wave_t(
        env, [(e, f), (g, h), (f, g)] + ([(e, h)] if need_t else [])
    )
    t_out = prods[3] if need_t else jnp.zeros_like(prods[0])
    return jnp.stack([prods[0], prods[1], prods[2], t_out])


def _point_double_t(env, p, need_t: bool = True):
    """dbl-2008-hwcd, waved (all four wave-1 operands are squares).
    ``need_t=False`` as in _point_add_t: only the LAST doubling of a run
    (whose output feeds an addition) must produce T."""
    x1, y1, z1 = p[0], p[1], p[2]
    bt = x1.shape[-1]
    xy = _add_t(x1, y1)
    a, b, zz, xy2 = _mul_wave_t(
        env, [(x1, x1), (y1, y1), (z1, z1), (xy, xy)]
    )
    c = _dbl_small_t(zz)
    h, g = _lin_wave_t([a + b, a + (env.subpad - b)], bt)
    e, f = _lin_wave_t([h + (env.subpad - xy2), c + g], bt)
    prods = _mul_wave_t(
        env, [(e, f), (g, h), (f, g)] + ([(e, h)] if need_t else [])
    )
    t_out = prods[3] if need_t else jnp.zeros_like(prods[0])
    return jnp.stack([prods[0], prods[1], prods[2], t_out])


def _identity_t(bt):
    zeros = jnp.zeros((NL, bt), jnp.int32)
    # iota mask instead of .at[].set — scatter has no Mosaic lowering
    limb0 = jax.lax.broadcasted_iota(jnp.int32, (NL, bt), 0) == 0
    one = jnp.where(limb0, 1, 0)
    return jnp.stack([zeros, one, one, zeros])


def _build_entries_t(env, a_point, bt):
    """A-multiples table [0]A..[15]A for the tournament select.

    The chain is built with PLAIN-T points (each add's p operand), with
    q = A carrying T*2d; at the end every entry's T is converted to T*2d
    in ONE wide mul against the broadcast d2 column, because entries are
    only ever consumed as the q operand of _point_add_t (identity's T2d
    is 0, so it needs no conversion)."""
    a2d = jnp.stack(
        [
            a_point[0],
            a_point[1],
            a_point[2],
            _mul_t(env, a_point[3], env.d2),
        ]
    )
    chain = [a_point]
    for _ in range(2, 1 << curve.WINDOW):
        chain.append(_point_add_t(env, chain[-1], a2d))
    ts2d = _mul_t(env, jnp.concatenate([c[3] for c in chain], axis=-1), env.d2)
    return [_identity_t(bt)] + [
        jnp.stack([c[0], c[1], c[2], ts2d[..., i * bt : (i + 1) * bt]])
        for i, c in enumerate(chain)
    ]


def _tournament_select(entries, nibble):
    """entries: list of 16 [4, NL, Bt] points; nibble: [1, Bt] int32.
    4-level tournament of jnp.where — 15 selects instead of 16
    one-hot multiply-accumulates."""
    level = entries
    for bit in range(curve.WINDOW):
        mask = ((nibble >> bit) & 1)[None, :, :] != 0  # [1, 1, Bt]
        level = [
            jnp.where(mask, hi, lo)
            for lo, hi in zip(level[0::2], level[1::2])
        ]
    return level[0]


def _select_base_t(env, byte, bt):
    """Constant-table select via one-hot MXU matmul: [80, nent] @
    [nent, Bt] -> [4, NL, Bt] (nent = 256)."""
    nent = env.btab.shape[1]
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (nent, bt), 0) == byte
    ).astype(jnp.float32)
    sel = jax.lax.dot(
        env.btab, onehot, precision=_HIGH, preferred_element_type=jnp.float32
    )
    return sel.astype(jnp.int32).reshape(4, NL, bt)


# ---- in-kernel compressed-equality epilogue --------------------------------
#
# The XLA epilogue (curve.compressed_equals: Fermat inversion + canonical
# + compare) is ~265 SEQUENTIAL tiny ops on [batch, 20] arrays — each one
# an HBM round-trip, measured ~2 ms of the 256-vote QC's 5.2 ms device
# time (the Pallas scan itself is 3.3 ms).  Running the same chain inside
# the kernel keeps every intermediate in VMEM (~0.3 ms).  Limb-major
# ports of field.py's _chain/_strict/canonical/pow_inv (field.py:238-308);
# limbs ride axis -2 with static indices, so no gathers are needed.


def _chain_seq_t(z):
    """One sequential carry pass along the limb axis (field.py _chain)."""
    c = jnp.zeros_like(z[..., :1, :])
    outs = []
    for i in range(NL):
        x = z[..., i : i + 1, :] + c
        c = x >> F.LIMB_BITS  # arithmetic shift: floor for negatives
        outs.append(x & F.MASK)
    return outs, c


def _strict_t(z):
    """Loose-normalized -> strictly normalized (field.py _strict)."""
    outs, _ = _chain_seq_t(z)
    z = jnp.concatenate(outs, axis=-2)
    for _ in range(2):  # peel bit 255 (at most twice)
        top = z[..., NL - 1 :, :] >> F.TOP_SHIFT
        z = jnp.concatenate(
            [
                z[..., :1, :] + top * F.TOP_FOLD,
                z[..., 1 : NL - 1, :],
                z[..., NL - 1 :, :] - (top << F.TOP_SHIFT),
            ],
            axis=-2,
        )
        outs, _ = _chain_seq_t(z)
        z = jnp.concatenate(outs, axis=-2)
    return z


def _canonical_t(a):
    """Unique value in [0, p) (field.py canonical), limb-major."""
    a = _strict_t(a)
    for _ in range(2):
        borrow = jnp.zeros_like(a[..., :1, :])
        outs = []
        for i in range(NL):
            x = a[..., i : i + 1, :] - int(F.P_LIMBS[i]) + borrow
            borrow = x >> F.LIMB_BITS
            outs.append(x & F.MASK)
        diff = jnp.concatenate(outs, axis=-2)
        a = jnp.where(borrow >= 0, diff, a)  # no final borrow -> a >= p
    return a


def _pow_inv_t(env, a):
    """a^(p-2) = a^-1, the standard curve25519 chain (field.py pow_inv).

    The long squaring runs are ``fori_loop``s, NOT unrolled: unrolling
    puts ~254 full multiplier bodies into one Mosaic kernel, which blew
    both the compile time (>35 min, aborted) and the scoped-VMEM stack
    (21.7M > 16M cap) when this epilogue was first fused in."""

    def sqr_n(x, n):
        if n < 4:
            for _ in range(n):
                x = _mul_t(env, x, x)
            return x
        return jax.lax.fori_loop(0, n, lambda i, v: _mul_t(env, v, v), x)

    z2 = _mul_t(env, a, a)
    z9 = _mul_t(env, sqr_n(z2, 2), a)
    z11 = _mul_t(env, z9, z2)
    z2_5_0 = _mul_t(env, _mul_t(env, z11, z11), z9)
    z2_10_0 = _mul_t(env, sqr_n(z2_5_0, 5), z2_5_0)
    z2_20_0 = _mul_t(env, sqr_n(z2_10_0, 10), z2_10_0)
    z2_40_0 = _mul_t(env, sqr_n(z2_20_0, 20), z2_20_0)
    z2_50_0 = _mul_t(env, sqr_n(z2_40_0, 10), z2_10_0)
    z2_100_0 = _mul_t(env, sqr_n(z2_50_0, 50), z2_50_0)
    z2_200_0 = _mul_t(env, sqr_n(z2_100_0, 100), z2_100_0)
    z2_250_0 = _mul_t(env, sqr_n(z2_200_0, 50), z2_50_0)
    return _mul_t(env, sqr_n(z2_250_0, 5), z11)


def _compressed_equals_t(env, p, r_y, r_sign):
    """Does each lane of ``p`` (X, Y, Z rows of a [4, NL, Bt] stack)
    compress to (r_y, r_sign)?  Returns int32 [1, Bt] 0/1.  Same
    semantics as curve.compressed_equals — r_y is the RAW 13-bit split
    of the encoding's low 255 bits (never reduced), so non-canonical
    encodings can never match."""
    zinv = _pow_inv_t(env, p[2])
    x, y = _mul_wave_t(env, [(p[0], zinv), (p[1], zinv)])
    y_ok = jnp.all(_canonical_t(y) == r_y, axis=-2, keepdims=True)
    sign_ok = (_canonical_t(x)[..., :1, :] & 1) == r_sign
    return (y_ok & sign_ok).astype(jnp.int32)


# ---- the kernel ------------------------------------------------------------


def _dsm_scan(env, ax, ay, az, at, s_bytes, k_hi, k_lo):
    """The 32-macro-step Straus scan: P = [s]B + [k]A for one tile.
    Returns the accumulator stack [4, NL, Bt] (T not computed).

    ax..at: [NL, Bt] limbs of A (the negated public keys).
    s_bytes: [NWIN/2, Bt] MSB-first 8-bit windows of s.
    k_hi, k_lo: [NWIN/2, Bt] MSB-first 4-bit window pairs of k.
    """
    bt = ax.shape[-1]
    a_point = jnp.stack([ax[:], ay[:], az[:], at[:]])

    entries = _build_entries_t(env, a_point, bt)

    nsteps = curve.NWIN // 2

    def step(i, acc):
        # dynamic row reads from the refs (dynamic_slice on values has
        # no Mosaic lowering; ref indexing with pl.ds does)
        sb = s_bytes[pl.ds(i, 1), :]  # [1, Bt]
        wh = k_hi[pl.ds(i, 1), :]
        wl = k_lo[pl.ds(i, 1), :]
        # need_t schedule: doublings ignore input T, additions consume
        # it — so only the last doubling of each run and the addition
        # feeding another addition produce T (8 muls saved per step)
        for j in range(curve.WINDOW):
            acc = _point_double_t(env, acc, need_t=j == curve.WINDOW - 1)
        acc = _point_add_t(
            env, acc, _tournament_select(entries, wh), need_t=False
        )
        for j in range(curve.WINDOW):
            acc = _point_double_t(env, acc, need_t=j == curve.WINDOW - 1)
        acc = _point_add_t(env, acc, _tournament_select(entries, wl))
        acc = _point_add_t(env, acc, _select_base_t(env, sb, bt), need_t=False)
        return acc

    return jax.lax.fori_loop(0, nsteps, step, _identity_t(bt))


def _dsm_kernel(
    btab, d2, subpad, ax, ay, az, at, s_bytes, k_hi, k_lo, ox, oy, oz, ot
):
    """Coordinate-output tile kernel (parity tests; the production
    verify path uses _dsm_verify_kernel, which fuses the epilogue)."""
    env = _Env(btab[:], d2[:], subpad[:])
    out = _dsm_scan(env, ax, ay, az, at, s_bytes, k_hi, k_lo)
    ox[:] = out[0]
    oy[:] = out[1]
    oz[:] = out[2]
    ot[:] = out[3]


def _dsm_verify_kernel(
    btab, d2, subpad, ax, ay, az, at, s_bytes, k_hi, k_lo, r_y, r_sign, ok
):
    """Fused tile kernel: Straus scan + in-VMEM compressed-equality.
    r_y: [NL, Bt] raw limb split of each R encoding; r_sign: [1, Bt];
    ok: [1, Bt] int32 0/1 output."""
    env = _Env(btab[:], d2[:], subpad[:])
    out = _dsm_scan(env, ax, ay, az, at, s_bytes, k_hi, k_lo)
    ok[:] = _compressed_equals_t(env, out, r_y[:], r_sign[:])


@partial(jax.jit, static_argnames=("interpret",))
def dual_scalar_mult(s_win, k_win, a_point, *, interpret: bool = False):
    """Drop-in for curve.dual_scalar_mult, Pallas-accelerated.

    s_win, k_win: int32 [NWIN, batch] MSB-first 4-bit windows.
    a_point: (X, Y, Z, T) with coords [batch, NL].
    Returns (X, Y, Z, T) with coords [batch, NL] — T is NOT computed
    (zeros): the only consumer, compressed_equals, reads X/Y/Z, and the
    scan's need_t schedule skips the final extended coordinate (one mul
    per point op saved).
    batch must be a multiple of LANE_TILE (the BatchVerifier pads).
    """
    batch = s_win.shape[1]
    bt = BT if batch % BT == 0 else LANE_TILE
    if batch % bt:
        raise ValueError(f"batch {batch} not a multiple of {bt}")

    # pair 4-bit windows into the kernel's layout
    s_pairs = s_win.reshape(curve.NWIN // 2, 2, batch)
    s_bytes = s_pairs[:, 0] * (1 << curve.WINDOW) + s_pairs[:, 1]
    k_pairs = k_win.reshape(curve.NWIN // 2, 2, batch)

    coords_t = [jnp.transpose(c) for c in a_point]  # [NL, batch]

    grid = (batch // bt,)

    def const_spec(shape):
        return pl.BlockSpec(
            shape, lambda i: (0, 0), memory_space=pltpu.VMEM
        )

    limb_spec = pl.BlockSpec(
        (NL, bt), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    win_spec = pl.BlockSpec(
        (curve.NWIN // 2, bt), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct((NL, batch), jnp.int32)

    ox, oy, oz, ot = pl.pallas_call(
        _dsm_kernel,
        grid=grid,
        in_specs=[
            const_spec(_BTAB_T.shape),
            const_spec(_D2_COL.shape),
            const_spec(_SUBPAD_COL.shape),
        ]
        + [limb_spec] * 4
        + [win_spec] * 3,
        out_specs=[limb_spec] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(
        jnp.asarray(_BTAB_T),
        jnp.asarray(_D2_COL),
        jnp.asarray(_SUBPAD_COL),
        *coords_t,
        s_bytes,
        k_pairs[:, 0],
        k_pairs[:, 1],
    )

    return tuple(jnp.transpose(c) for c in (ox, oy, oz, ot))


@partial(jax.jit, static_argnames=("interpret",))
def verify_compressed(
    s_win, k_win, a_point, r_y, r_sign, *, interpret: bool = False
):
    """Fused production path: dual_scalar_mult + compressed_equals in ONE
    Pallas dispatch.  Same operand contract as dual_scalar_mult, plus
    r_y [batch, NL] (raw limb split of each R encoding's low 255 bits)
    and r_sign [batch] (bit 255).  Returns bool [batch].

    Why fused: the XLA epilogue is ~265 SEQUENTIAL tiny field ops
    (Fermat inversion + canonical), each an HBM round-trip — measured
    ~2 ms of the 256-vote QC's device time; in-VMEM it is ~0.3 ms."""
    batch = s_win.shape[1]
    bt = BT if batch % BT == 0 else LANE_TILE
    if batch % bt:
        raise ValueError(f"batch {batch} not a multiple of {bt}")

    s_pairs = s_win.reshape(curve.NWIN // 2, 2, batch)
    s_bytes = s_pairs[:, 0] * (1 << curve.WINDOW) + s_pairs[:, 1]
    k_pairs = k_win.reshape(curve.NWIN // 2, 2, batch)
    coords_t = [jnp.transpose(c) for c in a_point]  # [NL, batch]

    grid = (batch // bt,)

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)

    limb_spec = pl.BlockSpec(
        (NL, bt), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    win_spec = pl.BlockSpec(
        (curve.NWIN // 2, bt), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    row_spec = pl.BlockSpec((1, bt), lambda i: (0, i), memory_space=pltpu.VMEM)

    (ok,) = pl.pallas_call(
        _dsm_verify_kernel,
        grid=grid,
        in_specs=[
            const_spec(_BTAB_T.shape),
            const_spec(_D2_COL.shape),
            const_spec(_SUBPAD_COL.shape),
        ]
        + [limb_spec] * 4
        + [win_spec] * 3
        + [limb_spec, row_spec],
        out_specs=[row_spec],
        out_shape=[jax.ShapeDtypeStruct((1, batch), jnp.int32)],
        interpret=interpret,
    )(
        jnp.asarray(_BTAB_T),
        jnp.asarray(_D2_COL),
        jnp.asarray(_SUBPAD_COL),
        *coords_t,
        s_bytes,
        k_pairs[:, 0],
        k_pairs[:, 1],
        jnp.transpose(r_y),
        r_sign.reshape(1, batch),
    )
    return ok[0] != 0
