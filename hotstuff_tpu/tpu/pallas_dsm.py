"""Pallas TPU kernel for the fused double-scalar multiplication.

This is the VMEM-resident rewrite of ``curve.dual_scalar_mult`` — the
hot loop of batched Ed25519 verification (reference hot spot:
``Signature::verify_batch``, crypto/src/lib.rs:213-226).  The XLA
version is HBM-bound: every field op round-trips intermediates through
HBM, and slope-timing on hardware shows elementwise throughput pinned
at memory bandwidth.  Here the whole 32-macro-step Straus scan runs
inside ONE kernel with every intermediate in VMEM.

Layout: limb-major ``[NLIMBS, Bt]`` — the batch tile rides the 128-wide
lane dimension (full VPU utilization), limbs ride sublanes.  The
schoolbook-product collapse is a constant one-hot matmul on the MXU
(``[39, 400] @ [400, Bt]``), exact in f32 by the bound analysis in
tpu/field.py.  Per-batch table selects use a 4-level tournament of
``jnp.where`` (15 selects of a [4, 20, Bt] entry vs 16 one-hot
multiply-adds).  Constant matrices (collapse weights, base-point
table, curve constant, subtraction pad) are kernel INPUTS — Pallas
kernels cannot capture traced constants — mapped to block (0, 0) so
every grid tile reads the same copy.

The kernel computes P = [s]B + [k]A for the whole tile; compressed-
encoding comparison (pow_inv etc.) stays in the XLA path — it is a few
percent of total time.  Correctness oracle: ``curve.dual_scalar_mult``
(itself RFC-8032-vector-tested); parity is tested in interpret mode on
CPU and on device in tests/test_tpu_ed25519.py.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import curve, field as F

NL = F.NLIMBS  # 20
NCOLS = 2 * NL - 1  # 39
LANE_TILE = 128  # minimum batch tile (lane width)
BT = 256  # batch tile: [20, 256] int32 = 3x2 vregs per coord
# Wide tile for the split kernel ONLY: a 256-signature QC doubles to 512
# half-scalar rows; one 512-lane tile runs them in a single 16-step scan
# instead of two sequential 256-row grid tiles (which would cost the
# same wall time as the unsplit 32-step kernel).  The Mosaic compile of
# this shape is slow (tens of minutes) but one-time now that the
# persistent compilation cache actually engages (see tpu/__init__.py).
SPLIT_BT = 512


def split_half_tile(n_pad: int) -> int:
    """Interleave unit for ``prepare_split``: lo/hi halves are laid out
    per KERNEL tile, so the unit must match the tile the kernel will
    pick for ``rows = 2*n_pad`` — 256 (tile 512) when it divides evenly,
    else 128 (tile 256).  Single source of truth for both sides."""
    return SPLIT_BT // 2 if n_pad % (SPLIT_BT // 2) == 0 else BT // 2

_HIGH = jax.lax.Precision.HIGHEST

# Host-side constants (numpy; shipped to the kernel as inputs).
_WT = F.W_CONV.T.copy()  # [39, 400] collapse matrix, limb-major
_BTAB_T = (
    np.asarray(curve.B_TABLE8, np.float32)  # [256, 4, 20]
    .reshape(1 << curve.B_WINDOW, 4 * NL)
    .T.copy()
)  # [80, 256]; limb values < 2^13+608 are f32-exact
_D2_COL = curve.D2_LIMBS.reshape(NL, 1)  # curve constant 2d, limb-major
_SUBPAD_COL = F.SUB_PAD.reshape(NL, 1)
# Doubled base table for the split-scalar kernel: entries 0..255 are
# [m]B, entries 256..511 are [m](2^128 B); hi-half rows offset their
# window byte by 256 to land in the second half.
_BTAB2_T = (
    np.concatenate(
        [np.asarray(curve.B_TABLE8), np.asarray(curve.B128_TABLE8)], axis=0
    )
    .astype(np.float32)
    .reshape(2 << curve.B_WINDOW, 4 * NL)
    .T.copy()
)  # [80, 512]


class _Env:
    """Kernel-side handles to the constant inputs."""

    def __init__(self, wt, btab, d2, subpad):
        self.wt = wt  # [39, 400] f32
        self.btab = btab  # [80, 256] f32
        self.d2 = d2  # [NL, 1] int32
        self.subpad = subpad  # [NL, 1] int32


# ---- limb-major field ops (values, not refs; all [NL, Bt]) -----------------


def _carry_t(z, passes: int):
    """Parallel carry passes along axis -2 (the limb axis)."""
    if z.shape[-2] > NL:
        lo = z[..., :NL, :]
        hi = z[..., NL:, :]
        hi_lo = (hi & F.MASK) * F.FOLD
        hi_hi = (hi >> F.LIMB_BITS) * F.FOLD
        nhi = z.shape[-2] - NL
        pad = [(0, 0)] * (z.ndim - 2)
        add0 = jnp.pad(hi_lo, pad + [(0, NL - nhi), (0, 0)])
        add1 = jnp.pad(hi_hi, pad + [(1, NL - nhi - 1), (0, 0)])
        z = lo + add0 + add1
    for _ in range(passes):
        r = jnp.concatenate(
            [z[..., : NL - 1, :] & F.MASK, z[..., NL - 1 :, :] & F.TOP_MASK],
            axis=-2,
        )
        c = z[..., : NL - 1, :] >> F.LIMB_BITS
        c_top = (z[..., NL - 1 :, :] >> F.TOP_SHIFT) * F.TOP_FOLD
        z = jnp.concatenate([r[..., :1, :] + c_top, r[..., 1:, :] + c], axis=-2)
    return z


def _mul_t(env, a, b):
    """[NL, Bt] x [NL, Bt] -> [NL, Bt]; conv collapse on the MXU."""
    bt = a.shape[-1]
    outer = (a[:, None, :] * b[None, :, :]).reshape(NL * NL, bt)
    lo = (outer & F.MASK).astype(jnp.float32)
    hi = (outer >> F.LIMB_BITS).astype(jnp.float32)
    slo = jax.lax.dot(
        env.wt, lo, precision=_HIGH, preferred_element_type=jnp.float32
    )
    shi = jax.lax.dot(
        env.wt, hi, precision=_HIGH, preferred_element_type=jnp.float32
    )
    prod = slo.astype(jnp.int32) + (shi.astype(jnp.int32) << F.LIMB_BITS)
    return _carry_t(prod, passes=4)


def _add_t(a, b):
    return _carry_t(a + b, passes=2)


def _sub_t(env, a, b):
    return _carry_t(a + (env.subpad - b), passes=2)


def _dbl_small_t(a):
    return _carry_t(a * jnp.int32(2), passes=2)


# ---- limb-major point ops: points are [4, NL, Bt] stacks (X, Y, Z, T) ------


def _point_add_t(env, p, q, need_t: bool = True):
    """Unified extended-coordinate addition (add-2008-hwcd-3).

    ``need_t=False`` skips producing the T coordinate (one mul):
    doublings ignore their input's T, so an addition feeding a doubling
    run — or the final scan output, which only X/Y/Z reach — never
    needs it.  The slot is zero-filled to keep the carry shape."""
    x1, y1, z1, t1 = p[0], p[1], p[2], p[3]
    x2, y2, z2, t2 = q[0], q[1], q[2], q[3]
    a = _mul_t(env, _sub_t(env, y1, x1), _sub_t(env, y2, x2))
    b = _mul_t(env, _add_t(y1, x1), _add_t(y2, x2))
    c = _mul_t(env, _mul_t(env, t1, t2), env.d2)
    d = _dbl_small_t(_mul_t(env, z1, z2))
    e = _sub_t(env, b, a)
    f = _sub_t(env, d, c)
    g = _add_t(d, c)
    h = _add_t(b, a)
    t_out = _mul_t(env, e, h) if need_t else jnp.zeros_like(e)
    return jnp.stack(
        [_mul_t(env, e, f), _mul_t(env, g, h), _mul_t(env, f, g), t_out]
    )


def _point_double_t(env, p, need_t: bool = True):
    """dbl-2008-hwcd.  ``need_t=False`` as in _point_add_t: only the
    LAST doubling of a run (whose output feeds an addition) must
    produce T."""
    x1, y1, z1 = p[0], p[1], p[2]
    a = _mul_t(env, x1, x1)
    b = _mul_t(env, y1, y1)
    c = _dbl_small_t(_mul_t(env, z1, z1))
    h = _add_t(a, b)
    xy = _add_t(x1, y1)
    e = _sub_t(env, h, _mul_t(env, xy, xy))
    g = _sub_t(env, a, b)
    f = _add_t(c, g)
    t_out = _mul_t(env, e, h) if need_t else jnp.zeros_like(e)
    return jnp.stack(
        [_mul_t(env, e, f), _mul_t(env, g, h), _mul_t(env, f, g), t_out]
    )


def _identity_t(bt):
    zeros = jnp.zeros((NL, bt), jnp.int32)
    # iota mask instead of .at[].set — scatter has no Mosaic lowering
    limb0 = jax.lax.broadcasted_iota(jnp.int32, (NL, bt), 0) == 0
    one = jnp.where(limb0, 1, 0)
    return jnp.stack([zeros, one, one, zeros])


def _tournament_select(entries, nibble):
    """entries: list of 16 [4, NL, Bt] points; nibble: [1, Bt] int32.
    4-level tournament of jnp.where — 15 selects instead of 16
    one-hot multiply-accumulates."""
    level = entries
    for bit in range(curve.WINDOW):
        mask = ((nibble >> bit) & 1)[None, :, :] != 0  # [1, 1, Bt]
        level = [
            jnp.where(mask, hi, lo)
            for lo, hi in zip(level[0::2], level[1::2])
        ]
    return level[0]


def _select_base_t(env, byte, bt):
    """Constant-table select via one-hot MXU matmul: [80, nent] @
    [nent, Bt] -> [4, NL, Bt] (nent = 256, or 512 for the split kernel's
    doubled table)."""
    nent = env.btab.shape[1]
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (nent, bt), 0) == byte
    ).astype(jnp.float32)
    sel = jax.lax.dot(
        env.btab, onehot, precision=_HIGH, preferred_element_type=jnp.float32
    )
    return sel.astype(jnp.int32).reshape(4, NL, bt)


# ---- the kernel ------------------------------------------------------------


def _dsm_kernel(
    wt, btab, d2, subpad, ax, ay, az, at, s_bytes, k_hi, k_lo, ox, oy, oz, ot
):
    """One batch tile: P = [s]B + [k]A.

    wt/btab/d2/subpad: constant inputs (same block for every tile).
    ax..at: [NL, Bt] limbs of A (the negated public keys).
    s_bytes: [NWIN/2, Bt] MSB-first 8-bit windows of s.
    k_hi, k_lo: [NWIN/2, Bt] MSB-first 4-bit window pairs of k.
    ox..ot: [NL, Bt] output extended coordinates.
    """
    env = _Env(wt[:], btab[:], d2[:], subpad[:])
    bt = ax.shape[-1]
    a_point = jnp.stack([ax[:], ay[:], az[:], at[:]])

    # A-multiples table [0]A..[15]A (unified add handles the identity)
    entries = [_identity_t(bt), a_point]
    for _ in range(2, 1 << curve.WINDOW):
        entries.append(_point_add_t(env, entries[-1], a_point))

    nsteps = curve.NWIN // 2

    def step(i, acc):
        # dynamic row reads from the refs (dynamic_slice on values has
        # no Mosaic lowering; ref indexing with pl.ds does)
        sb = s_bytes[pl.ds(i, 1), :]  # [1, Bt]
        wh = k_hi[pl.ds(i, 1), :]
        wl = k_lo[pl.ds(i, 1), :]
        # need_t schedule: doublings ignore input T, additions consume
        # it — so only the last doubling of each run and the addition
        # feeding another addition produce T (8 muls saved per step)
        for j in range(curve.WINDOW):
            acc = _point_double_t(env, acc, need_t=j == curve.WINDOW - 1)
        acc = _point_add_t(
            env, acc, _tournament_select(entries, wh), need_t=False
        )
        for j in range(curve.WINDOW):
            acc = _point_double_t(env, acc, need_t=j == curve.WINDOW - 1)
        acc = _point_add_t(env, acc, _tournament_select(entries, wl))
        acc = _point_add_t(env, acc, _select_base_t(env, sb, bt), need_t=False)
        return acc

    out = jax.lax.fori_loop(0, nsteps, step, _identity_t(bt))
    ox[:] = out[0]
    oy[:] = out[1]
    oz[:] = out[2]
    ot[:] = out[3]


def _dsm_kernel_split(
    wt, btab, d2, subpad, ax, ay, az, at, s_bytes, k_hi, k_lo, base_off,
    ox, oy, oz, ot,
):
    """Split-scalar tile: rows [0 : Bt/2] are the 128-bit LO halves of
    Bt/2 signatures, rows [Bt/2 : Bt] the HI halves ([s_hi](2^128 B) +
    [k_hi](-2^128 A), with the A-multiples supplied per row and the
    base-table window byte offset by base_off into the doubled constant
    table).  The scan is 16 macro steps instead of 32; the halves are
    recombined in-tile with one final addition, so the output batch is
    Bt/2.  ~2x lower scan depth for any QC whose doubled row count fits
    one tile (<= 128 votes at Bt = 256)."""
    env = _Env(wt[:], btab[:], d2[:], subpad[:])
    bt = ax.shape[-1]
    a_point = jnp.stack([ax[:], ay[:], az[:], at[:]])

    entries = [_identity_t(bt), a_point]
    for _ in range(2, 1 << curve.WINDOW):
        entries.append(_point_add_t(env, entries[-1], a_point))

    nsteps = s_bytes.shape[0]
    off = base_off[:]  # [1, Bt]

    def step(i, acc, last_t):
        sb = s_bytes[pl.ds(i, 1), :] + off
        wh = k_hi[pl.ds(i, 1), :]
        wl = k_lo[pl.ds(i, 1), :]
        for j in range(curve.WINDOW):
            acc = _point_double_t(env, acc, need_t=j == curve.WINDOW - 1)
        acc = _point_add_t(
            env, acc, _tournament_select(entries, wh), need_t=False
        )
        for j in range(curve.WINDOW):
            acc = _point_double_t(env, acc, need_t=j == curve.WINDOW - 1)
        acc = _point_add_t(env, acc, _tournament_select(entries, wl))
        # only the FINAL step's base addition needs T (the recombining
        # addition consumes it; intermediate T feeds doublings, which
        # ignore it)
        acc = _point_add_t(
            env, acc, _select_base_t(env, sb, bt), need_t=last_t
        )
        return acc

    acc = jax.lax.fori_loop(
        0, nsteps - 1, lambda i, a: step(i, a, False), _identity_t(bt)
    )
    acc = step(nsteps - 1, acc, True)
    half = bt // 2
    lo = acc[:, :, :half]
    hi = acc[:, :, half:]
    out = _point_add_t(env, lo, hi, need_t=False)
    ox[:] = out[0]
    oy[:] = out[1]
    oz[:] = out[2]
    ot[:] = out[3]


@partial(jax.jit, static_argnames=("interpret",))
def dual_scalar_mult_split(
    s_win, k_win, a_point, base_off, *, interpret: bool = False
):
    """Split-scalar variant: operands are PER-HALF rows.

    s_win, k_win: int32 [32, R] MSB-first 4-bit windows of the 128-bit
    scalar halves; a_point: (X, Y, Z, T) coords [R, NL] of the negated
    per-half A points; base_off: int32 [R], 0 for lo rows / 256 for hi.
    R must be a multiple of BT.  The kernel tile is
    ``2 * split_half_tile(R // 2)`` (512 when R divides evenly, else
    256) and each TILE-row block must hold the lo halves of tile/2
    signatures followed by their hi halves — interleave with
    ``split_half_tile`` as the unit, exactly as ``prepare_split`` does;
    a fixed 128-unit interleave at R = 512 would silently pair wrong
    lo/hi halves.  Returns (X, Y, Z, T) with coords [R/2, NL]; T is NOT
    computed (zeros)."""
    rows = s_win.shape[1]
    if rows % BT:
        raise ValueError(f"rows {rows} not a multiple of {BT}")
    tile = 2 * split_half_tile(rows // 2)
    nwin = s_win.shape[0]
    s_pairs = s_win.reshape(nwin // 2, 2, rows)
    s_bytes = s_pairs[:, 0] * (1 << curve.WINDOW) + s_pairs[:, 1]
    k_pairs = k_win.reshape(nwin // 2, 2, rows)

    coords_t = [jnp.transpose(c) for c in a_point]  # [NL, rows]

    grid = (rows // tile,)

    def const_spec(shape):
        return pl.BlockSpec(shape, lambda i: (0, 0), memory_space=pltpu.VMEM)

    limb_spec = pl.BlockSpec(
        (NL, tile), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    win_spec = pl.BlockSpec(
        (nwin // 2, tile), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    off_spec = pl.BlockSpec((1, tile), lambda i: (0, i), memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec(
        (NL, tile // 2), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct((NL, rows // 2), jnp.int32)

    ox, oy, oz, ot = pl.pallas_call(
        _dsm_kernel_split,
        grid=grid,
        in_specs=[
            const_spec(_WT.shape),
            const_spec(_BTAB2_T.shape),
            const_spec(_D2_COL.shape),
            const_spec(_SUBPAD_COL.shape),
        ]
        + [limb_spec] * 4
        + [win_spec] * 3
        + [off_spec],
        out_specs=[out_spec] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(
        jnp.asarray(_WT),
        jnp.asarray(_BTAB2_T),
        jnp.asarray(_D2_COL),
        jnp.asarray(_SUBPAD_COL),
        *coords_t,
        s_bytes,
        k_pairs[:, 0],
        k_pairs[:, 1],
        base_off.reshape(1, rows),
    )

    return tuple(jnp.transpose(c) for c in (ox, oy, oz, ot))


@partial(jax.jit, static_argnames=("interpret",))
def dual_scalar_mult(s_win, k_win, a_point, *, interpret: bool = False):
    """Drop-in for curve.dual_scalar_mult, Pallas-accelerated.

    s_win, k_win: int32 [NWIN, batch] MSB-first 4-bit windows.
    a_point: (X, Y, Z, T) with coords [batch, NL].
    Returns (X, Y, Z, T) with coords [batch, NL] — T is NOT computed
    (zeros): the only consumer, compressed_equals, reads X/Y/Z, and the
    scan's need_t schedule skips the final extended coordinate (one mul
    per point op saved).
    batch must be a multiple of LANE_TILE (the BatchVerifier pads).
    """
    batch = s_win.shape[1]
    bt = BT if batch % BT == 0 else LANE_TILE
    if batch % bt:
        raise ValueError(f"batch {batch} not a multiple of {bt}")

    # pair 4-bit windows into the kernel's layout
    s_pairs = s_win.reshape(curve.NWIN // 2, 2, batch)
    s_bytes = s_pairs[:, 0] * (1 << curve.WINDOW) + s_pairs[:, 1]
    k_pairs = k_win.reshape(curve.NWIN // 2, 2, batch)

    coords_t = [jnp.transpose(c) for c in a_point]  # [NL, batch]

    grid = (batch // bt,)

    def const_spec(shape):
        return pl.BlockSpec(
            shape, lambda i: (0, 0), memory_space=pltpu.VMEM
        )

    limb_spec = pl.BlockSpec(
        (NL, bt), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    win_spec = pl.BlockSpec(
        (curve.NWIN // 2, bt), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct((NL, batch), jnp.int32)

    ox, oy, oz, ot = pl.pallas_call(
        _dsm_kernel,
        grid=grid,
        in_specs=[
            const_spec(_WT.shape),
            const_spec(_BTAB_T.shape),
            const_spec(_D2_COL.shape),
            const_spec(_SUBPAD_COL.shape),
        ]
        + [limb_spec] * 4
        + [win_spec] * 3,
        out_specs=[limb_spec] * 4,
        out_shape=[out_shape] * 4,
        interpret=interpret,
    )(
        jnp.asarray(_WT),
        jnp.asarray(_BTAB_T),
        jnp.asarray(_D2_COL),
        jnp.asarray(_SUBPAD_COL),
        *coords_t,
        s_bytes,
        k_pairs[:, 0],
        k_pairs[:, 1],
    )

    return tuple(jnp.transpose(c) for c in (ox, oy, oz, ot))
