"""Edwards25519 point arithmetic and fused double-scalar multiplication.

TPU-first design notes:
- Points are extended homogeneous coordinates (X:Y:Z:T) with each
  coordinate a [..., 20]-limb int32 array (see tpu/field.py). All batch
  axes vectorize through the limb ops directly — no vmap needed, the ops
  broadcast.
- The verification workhorse is a *fused* Straus/Shamir double-scalar
  multiplication [s]B + [k]A' evaluated by one `lax.scan` over 253 bit
  positions shared by the whole batch: per step one doubling and two
  arithmetically-selected additions. Data-dependent branching is replaced
  by `jnp.where` selects, keeping the graph static for XLA.
- There is deliberately no on-device decompression: committee public keys
  are decompressed once on the host (cached), and R is never decompressed
  at all — the kernel compares the *compressed encoding* of the computed
  point against the signature's R bytes (math in tpu/ed25519.py).

Formulas: extended-coordinate unified addition (add-2008-hwcd-3) and
doubling (dbl-2008-hwcd), mirroring the oracle in crypto/ed25519_ref.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519_ref as ref
from . import field as F

# Curve constant 2d in limbs.
D2_LIMBS = F.limbs_from_int(2 * ref.D % ref.P)

# Base point in extended affine limbs (Z=1).
_BX, _BY = ref.BASE_AFFINE
B_X = F.limbs_from_int(_BX)
B_Y = F.limbs_from_int(_BY)
B_T = F.limbs_from_int(_BX * _BY % ref.P)

NBITS = 253  # scalars are < L < 2^253

Point = tuple  # (X, Y, Z, T) limb arrays


def identity(shape_like) -> Point:
    """Identity point broadcast to the batch shape of ``shape_like``."""
    zeros = jnp.zeros_like(shape_like)
    one = zeros.at[..., 0].set(1)
    return (zeros, one, one, zeros)


def base_point(shape_like) -> Point:
    zeros = jnp.zeros_like(shape_like)
    return (
        zeros + jnp.asarray(B_X),
        zeros + jnp.asarray(B_Y),
        zeros.at[..., 0].set(1),
        zeros + jnp.asarray(B_T),
    )


def point_add(p: Point, q: Point) -> Point:
    """Unified addition (valid for doubling & identity), add-2008-hwcd-3."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = F.mul(F.sub(Y1, X1), F.sub(Y2, X2))
    b = F.mul(F.add(Y1, X1), F.add(Y2, X2))
    c = F.mul(F.mul(T1, T2), jnp.asarray(D2_LIMBS))
    d = F.mul_small(F.mul(Z1, Z2), 2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p: Point) -> Point:
    """Doubling, dbl-2008-hwcd."""
    X1, Y1, Z1, _ = p
    a = F.sqr(X1)
    b = F.sqr(Y1)
    c = F.mul_small(F.sqr(Z1), 2)
    h = F.add(a, b)
    e = F.sub(h, F.sqr(F.add(X1, Y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_select(flag, p: Point, q: Point) -> Point:
    """flag ? p : q, element-wise over the batch. flag: bool/int [...]."""
    m = flag[..., None] != 0
    return tuple(jnp.where(m, a, b) for a, b in zip(p, q))


def point_neg(p: Point) -> Point:
    X, Y, Z, T = p
    zero = jnp.zeros_like(X)
    return (F.sub(zero, X), Y, Z, F.sub(zero, T))


def dual_scalar_mult(s_bits, k_bits, a_point: Point) -> Point:
    """[s]B + [k]A for a whole batch at once.

    s_bits, k_bits: int32 [NBITS, ...batch] — MSB first.
    a_point: batch of points (each coord [...batch, 20]).
    Returns the batch of result points.

    One lax.scan step = 1 doubling + 2 selected additions; B is a
    compile-time constant, A rides in the closure (loop-invariant).
    """
    b_point = base_point(a_point[0])

    def step(acc, bits):
        bs, bk = bits
        acc = point_double(acc)
        with_b = point_add(acc, b_point)
        acc = point_select(bs, with_b, acc)
        with_a = point_add(acc, a_point)
        acc = point_select(bk, with_a, acc)
        return acc, None

    init = identity(a_point[0])
    out, _ = jax.lax.scan(step, init, (s_bits, k_bits))
    return out


def compressed_equals(p: Point, y_limbs, sign_bits):
    """Does ``p`` compress to (y_limbs, sign_bits)?

    y_limbs: raw 13-bit limb decomposition of the low 255 bits of the
    candidate encoding (NOT reduced mod p — a non-canonical y >= p can then
    never match, which is exactly RFC 8032's rejection of invalid
    encodings). sign_bits: int [...] in {0,1}, bit 255 of the encoding.
    """
    X, Y, Z, _ = p
    zinv = F.pow_inv(Z)
    x = F.mul(X, zinv)
    y = F.mul(Y, zinv)
    y_ok = jnp.all(F.canonical(y) == y_limbs, axis=-1)
    sign_ok = F.is_odd(x) == sign_bits
    return y_ok & sign_ok


# --- host-side helpers -------------------------------------------------------


def scalar_to_bits(s: int) -> np.ndarray:
    """Scalar -> MSB-first bit vector of length NBITS (int32)."""
    return np.array([(s >> (NBITS - 1 - i)) & 1 for i in range(NBITS)], np.int32)


def point_to_limbs(p: "ref.Point") -> tuple[np.ndarray, ...]:
    """Affine-ize a reference point and emit (X, Y, Z=1, T) limb vectors."""
    x, y = ref.point_affine(p)
    one = F.limbs_from_int(1)
    return (
        F.limbs_from_int(x),
        F.limbs_from_int(y),
        one,
        F.limbs_from_int(x * y % ref.P),
    )
