"""Edwards25519 point arithmetic and fused double-scalar multiplication.

TPU-first design notes:
- Points are extended homogeneous coordinates (X:Y:Z:T) with each
  coordinate a [..., 20]-limb int32 array (see tpu/field.py). All batch
  axes vectorize through the limb ops directly — no vmap needed, the ops
  broadcast.
- The verification workhorse is a *fused* Straus/Shamir double-scalar
  multiplication [s]B + [k]A' evaluated by one `lax.scan` over 253 bit
  positions shared by the whole batch: per step one doubling and two
  arithmetically-selected additions. Data-dependent branching is replaced
  by `jnp.where` selects, keeping the graph static for XLA.
- There is deliberately no on-device decompression: committee public keys
  are decompressed once on the host (cached), and R is never decompressed
  at all — the kernel compares the *compressed encoding* of the computed
  point against the signature's R bytes (math in tpu/ed25519.py).

Formulas: extended-coordinate unified addition (add-2008-hwcd-3) and
doubling (dbl-2008-hwcd), mirroring the oracle in crypto/ed25519_ref.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519_ref as ref
from . import field as F

# Curve constant 2d in limbs.
D2_LIMBS = F.limbs_from_int(2 * ref.D % ref.P)

# Base point in extended affine limbs (Z=1).
_BX, _BY = ref.BASE_AFFINE
B_X = F.limbs_from_int(_BX)
B_Y = F.limbs_from_int(_BY)
B_T = F.limbs_from_int(_BX * _BY % ref.P)

NBITS = 253  # scalars are < L < 2^253
WINDOW = 4  # Straus window width
NWIN = 64  # ceil(256 / WINDOW) windows, MSB-first (top 3 bits always 0)

Point = tuple  # (X, Y, Z, T) limb arrays


def _base_table(window: int, base: "ref.Point" = ref.B_POINT) -> np.ndarray:
    """Constant table of [m]P for m in 0..2^window-1, extended affine
    limbs.  Shape [2^window, 4, NLIMBS] (coords X, Y, Z=1, T)."""
    table = np.zeros((1 << window, 4, F.NLIMBS), np.int32)
    for m in range(1 << window):
        if m == 0:
            x, y = 0, 1
        else:
            x, y = ref.point_affine(ref.point_mul(m, base))
        table[m, 0] = F.limbs_from_int(x)
        table[m, 1] = F.limbs_from_int(y)
        table[m, 2] = F.limbs_from_int(1)
        table[m, 3] = F.limbs_from_int(x * y % ref.P)
    return table


# The base point is compile-time constant, so its window can be twice as
# wide for free (the table is baked into the program): 8-bit windows
# halve the number of [m]B additions in the fused scan (64 -> 32),
# measured ~8% off whole-kernel latency.
B_WINDOW = 8
B_TABLE8 = _base_table(B_WINDOW)


def identity(shape_like) -> Point:
    """Identity point broadcast to the batch shape of ``shape_like``."""
    zeros = jnp.zeros_like(shape_like)
    one = zeros.at[..., 0].set(1)
    return (zeros, one, one, zeros)


def base_point(shape_like) -> Point:
    zeros = jnp.zeros_like(shape_like)
    return (
        zeros + jnp.asarray(B_X),
        zeros + jnp.asarray(B_Y),
        zeros.at[..., 0].set(1),
        zeros + jnp.asarray(B_T),
    )


def point_add(p: Point, q: Point) -> Point:
    """Unified addition (valid for doubling & identity), add-2008-hwcd-3."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = F.mul(F.sub(Y1, X1), F.sub(Y2, X2))
    b = F.mul(F.add(Y1, X1), F.add(Y2, X2))
    c = F.mul(F.mul(T1, T2), jnp.asarray(D2_LIMBS))
    d = F.mul_small(F.mul(Z1, Z2), 2)
    e = F.sub(b, a)
    f = F.sub(d, c)
    g = F.add(d, c)
    h = F.add(b, a)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_double(p: Point) -> Point:
    """Doubling, dbl-2008-hwcd."""
    X1, Y1, Z1, _ = p
    a = F.sqr(X1)
    b = F.sqr(Y1)
    c = F.mul_small(F.sqr(Z1), 2)
    h = F.add(a, b)
    e = F.sub(h, F.sqr(F.add(X1, Y1)))
    g = F.sub(a, b)
    f = F.add(c, g)
    return (F.mul(e, f), F.mul(g, h), F.mul(f, g), F.mul(e, h))


def point_select(flag, p: Point, q: Point) -> Point:
    """flag ? p : q, element-wise over the batch. flag: bool/int [...]."""
    m = flag[..., None] != 0
    return tuple(jnp.where(m, a, b) for a, b in zip(p, q))


def point_neg(p: Point) -> Point:
    X, Y, Z, T = p
    zero = jnp.zeros_like(X)
    return (F.sub(zero, X), Y, Z, F.sub(zero, T))


def _build_a_table(a_point: Point) -> tuple:
    """[m]A for m in 0..15: coords stacked as [16, ...batch, 20].
    Unified addition is complete (handles identity), so no branches."""
    entries = [identity(a_point[0]), a_point]
    for _ in range(2, 1 << WINDOW):
        entries.append(point_add(entries[-1], a_point))
    return tuple(
        jnp.stack([e[c] for e in entries], axis=0) for c in range(4)
    )


def _select_from_batch_table(table: tuple, nibble) -> Point:
    """table: coords [16, ...batch, 20]; nibble: int32 [...batch] in 0..15.
    One-hot weighted sum — a 16-way select with no gather."""
    onehot = (
        nibble[None, ...] == jnp.arange(1 << WINDOW, dtype=jnp.int32).reshape(
            (1 << WINDOW,) + (1,) * nibble.ndim
        )
    ).astype(jnp.int32)[..., None]  # [16, ...batch, 1]
    return tuple(jnp.sum(coord * onehot, axis=0) for coord in table)


def _select_from_const_table(byte) -> Point:
    """B_TABLE8 select: byte [...batch] -> constant multiples of B.

    The 256-way select is a one-hot f32 matmul so it rides the MXU
    (limb values < 2^13 are f32-exact; the one-hot contraction picks a
    single entry, so no accumulation error is possible)."""
    onehot = (
        byte[..., None] == jnp.arange(1 << B_WINDOW, dtype=jnp.int32)
    ).astype(jnp.float32)  # [...batch, 256]
    tab = jnp.asarray(B_TABLE8, dtype=jnp.float32)  # [256, 4, 20]
    sel = jnp.tensordot(
        onehot, tab, axes=([-1], [0]), precision=jax.lax.Precision.HIGHEST
    ).astype(jnp.int32)  # [...batch, 4, 20]
    return tuple(sel[..., c, :] for c in range(4))


def dual_scalar_mult(s_win, k_win, a_point: Point) -> Point:
    """[s]B + [k]A for a whole batch at once — mixed-window Straus.

    s_win, k_win: int32 [NWIN, ...batch] — MSB-first 4-bit windows.
    a_point: batch of points (each coord [...batch, 20]).

    One lax.scan macro-step covers 8 bits: 2x(4 doublings + one
    [m]A addition from the 16-entry per-batch table) + one [m]B addition
    from the compile-time 256-entry constant table (B is fixed, so its
    window is twice as wide for free — 32 B-additions instead of 64).
    """
    a_table = _build_a_table(a_point)

    # pair the 4-bit windows: (hi, lo) nibbles of each 8-bit B-window
    s_pairs = s_win.reshape((NWIN // 2, 2) + s_win.shape[1:])
    s_bytes = s_pairs[:, 0] * (1 << WINDOW) + s_pairs[:, 1]
    k_pairs = k_win.reshape((NWIN // 2, 2) + k_win.shape[1:])

    def step(acc, wins):
        sb, wk_hi, wk_lo = wins
        for _ in range(WINDOW):
            acc = point_double(acc)
        acc = point_add(acc, _select_from_batch_table(a_table, wk_hi))
        for _ in range(WINDOW):
            acc = point_double(acc)
        acc = point_add(acc, _select_from_batch_table(a_table, wk_lo))
        acc = point_add(acc, _select_from_const_table(sb))
        return acc, None

    init = identity(a_point[0])
    out, _ = jax.lax.scan(
        step, init, (s_bytes, k_pairs[:, 0], k_pairs[:, 1])
    )
    return out


def compressed_equals(p: Point, y_limbs, sign_bits):
    """Does ``p`` compress to (y_limbs, sign_bits)?

    y_limbs: raw 13-bit limb decomposition of the low 255 bits of the
    candidate encoding (NOT reduced mod p — a non-canonical y >= p can then
    never match, which is exactly RFC 8032's rejection of invalid
    encodings). sign_bits: int [...] in {0,1}, bit 255 of the encoding.
    """
    X, Y, Z, _ = p
    zinv = F.pow_inv(Z)
    x = F.mul(X, zinv)
    y = F.mul(Y, zinv)
    y_ok = jnp.all(F.canonical(y) == y_limbs, axis=-1)
    sign_ok = F.is_odd(x) == sign_bits
    return y_ok & sign_ok


# --- host-side helpers -------------------------------------------------------


def scalar_to_bits(s: int) -> np.ndarray:
    """Scalar -> MSB-first bit vector of length NBITS (int32)."""
    return np.array([(s >> (NBITS - 1 - i)) & 1 for i in range(NBITS)], np.int32)


def point_to_limbs(p: "ref.Point") -> tuple[np.ndarray, ...]:
    """Affine-ize a reference point and emit (X, Y, Z=1, T) limb vectors."""
    x, y = ref.point_affine(p)
    one = F.limbs_from_int(1)
    return (
        F.limbs_from_int(x),
        F.limbs_from_int(y),
        one,
        F.limbs_from_int(x * y % ref.P),
    )
