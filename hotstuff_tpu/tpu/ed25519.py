"""Batched Ed25519 verification on TPU — the crypto hot kernel.

This is the TPU-native replacement for the reference's QC-verify hot spot
(``Signature::verify_batch``, reference crypto/src/lib.rs:213-226, called
from QC::verify at consensus/src/messages.rs:195) and the per-signature
verifies on the proposal path (messages.rs:64,142,256,305-311).

Verification equation: a signature (R, s) by pubkey A over message M is
valid iff [s]B == R + [k]A with k = SHA-512(R||A||M) mod L, i.e. iff
P := [s]B + [k](-A) compresses to the R bytes. The kernel evaluates P for
the whole batch with one fused double-scalar multiplication and compares
compressed encodings, so:

- SHA-512 and the mod-L reductions stay on the host (cheap, ~us each);
- committee public keys are decompressed ONCE on the host and cached —
  the committee is fixed per epoch, so steady-state verification does no
  square roots at all, on either side;
- R is never decompressed: the compressed-encoding comparison subsumes
  point validity (an R that decodes to no curve point can never equal a
  compressed P).

Semantics vs the CPU path: cofactorless ("strict") verification with
rejection of s >= L and non-canonical R encodings — agreeing with the
oracle `ed25519_ref.verify` on every input (tested in
tests/test_tpu_ed25519.py). Batches are padded to a small set of static
shapes to bound XLA recompilation.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519_ref as ref
from ..telemetry import spans as _spans
from . import curve, field as F

MASK255 = (1 << 255) - 1

# Padded batch shapes (powers of 4) to bound compilation count.
PAD_SIZES = (1, 4, 16, 64, 256, 1024, 4096)


def _verify_impl(ax, ay, az, at, s_bits, k_bits, r_y, r_sign):
    """Device kernel body: bool[batch] validity.

    ax..at: [batch, 20] limbs of the NEGATED public-key points.
    s_win, k_win: [NWIN, batch] MSB-first 4-bit scalar windows.
    r_y: [batch, 20] raw limb split of R's low 255 bits.
    r_sign: [batch] bit 255 of R.
    """
    p = curve.dual_scalar_mult(s_bits, k_bits, (ax, ay, az, at))
    return curve.compressed_equals(p, r_y, r_sign)


def _verify_impl_pallas(ax, ay, az, at, s_bits, k_bits, r_y, r_sign):
    """Same contract as _verify_impl, with the WHOLE verification —
    double-scalar multiplication AND the compressed-equality epilogue —
    fused into one VMEM-resident Pallas dispatch (tpu/pallas_dsm.py;
    the XLA epilogue was ~2 ms of sequential HBM round-trips).  TPU
    backend only; batch must be a multiple of pallas_dsm.LANE_TILE (the
    pad sizes guarantee it)."""
    from . import pallas_dsm

    return pallas_dsm.verify_compressed(
        s_bits, k_bits, (ax, ay, az, at), r_y, r_sign
    )


_verify_kernel = partial(jax.jit, static_argnames=())(_verify_impl)
_verify_kernel_pallas = partial(jax.jit, static_argnames=())(_verify_impl_pallas)

# Donated variants (ISSUE 6): the scalar windows, R limbs and sign bits
# are per-wave staging temporaries — donating them lets XLA reuse their
# device allocations across waves instead of re-allocating per dispatch.
# The point coordinates (args 0-3) stay un-donated: with the device key
# cache they alias the epoch-static gather source.
_verify_kernel_donated = jax.jit(_verify_impl, donate_argnums=(4, 5, 6, 7))
_verify_kernel_pallas_donated = jax.jit(
    _verify_impl_pallas, donate_argnums=(4, 5, 6, 7)
)


# Pallas pad shapes: lane-aligned, capped at 1024 per dispatch (larger
# batches chunk; each new shape costs a multi-minute Mosaic compile,
# amortized by the persistent compilation cache).
PALLAS_PAD_SIZES = (128, 256, 1024)


@jax.jit
def _gather_rows(tables, idxs):
    """Device-side committee-key gather (ISSUE 5): index the
    device-resident stacked point tables by row id, so a wave transfers
    [n] int64 indices instead of 4x[n,20] int32 coordinate rows."""
    return tuple(t[idxs] for t in tables)


def _bytes_to_limbs(b: bytes, lo_bits: int = 255) -> np.ndarray:
    v = int.from_bytes(b, "little") & ((1 << lo_bits) - 1)
    out = np.zeros(F.NLIMBS, np.int32)
    for i in range(F.NLIMBS):
        out[i] = v & F.MASK
        v >>= F.LIMB_BITS
    return out


_LIMB_WEIGHTS = (1 << np.arange(F.LIMB_BITS, dtype=np.int32)).astype(np.int32)

# big-endian bytes of the group order, for the vectorized s < L check
_L_BE = np.frombuffer(ref.L.to_bytes(32, "big"), np.uint8)


_WIN_WEIGHTS = (1 << np.arange(curve.WINDOW - 1, -1, -1)).astype(np.int32)


def _bytes_to_windows_msb(rows: np.ndarray) -> np.ndarray:
    """[n, W] little-endian scalar bytes -> [n, 2W] MSB-first 4-bit
    windows (W = 32 for full scalars < L < 2^253)."""
    bits = np.unpackbits(rows[:, ::-1], axis=1, bitorder="big").astype(np.int32)
    nwin = rows.shape[1] * 8 // curve.WINDOW
    groups = bits.reshape(rows.shape[0], nwin, curve.WINDOW)
    return groups @ _WIN_WEIGHTS


def _bytes_rows_to_limbs(rows: np.ndarray) -> np.ndarray:
    """[n, 32] little-endian encodings -> [n, NLIMBS] raw 13-bit split of
    the low 255 bits (NOT reduced mod p — see compressed_equals)."""
    bits = np.unpackbits(rows, axis=1, bitorder="little")[:, :255]
    bits = np.pad(bits, [(0, 0), (0, F.NLIMBS * F.LIMB_BITS - 255)])
    groups = bits.reshape(rows.shape[0], F.NLIMBS, F.LIMB_BITS).astype(np.int32)
    return groups @ _LIMB_WEIGHTS


class BatchVerifier:
    """Host-side driver: prepares batches, caches committee points, runs the
    jitted kernel. Thread-compatible with the asyncio node (pure function +
    caches keyed by immutable bytes).

    Hybrid routing: batches smaller than ``min_device_batch`` are
    verified on the CPU backend instead — kernel dispatch has a fixed
    cost (milliseconds under a remote tunnel, tens of microseconds
    co-located) that swamps the work of a handful of signatures, so the
    device only sees batches where it pays off.  Set
    ``min_device_batch=0`` to force everything onto the device (tests
    do, so the kernel path is what's exercised)."""

    def __init__(self, min_device_batch: int = 64, use_pallas: bool | None = None):
        # pk bytes -> (ax, ay, az, at) limb rows of the negated point, or None
        self._point_cache: dict[bytes, tuple | None] = {}
        # Vectorized prepare path: pk bytes -> row index into the stacked
        # point table (row 0 is a zero dummy for invalid items), rebuilt
        # lazily when new keys enter the cache.  Committee keys are fixed
        # per epoch, so steady state is one fancy-index gather per batch
        # instead of a per-item Python copy loop (measured 11-28 ms of
        # GIL-held prep per 736-sig wave before this).
        self._row_index: dict[bytes, int] = {}
        # the published build: (coordinate tables, row index) or None.
        # _table_lock serializes cache inserts/invalidation and rebuilds:
        # this object is shared between the event loop and the async
        # verify service's worker thread, and an unlocked rebuild racing
        # an insert can either crash (dict changed size during
        # iteration) or publish a build missing the new key while
        # clobbering the staleness marker — after which that key's valid
        # signatures map to the zero dummy row forever.
        import threading

        self._table_lock = threading.Lock()
        self._tables: tuple | None = None
        # Device-resident committee key cache (ISSUE 5): the stacked
        # coordinate tables staged on device ONCE per rebuild (committee
        # keys are static per epoch), so each wave ships only the [n]
        # row indices and gathers coordinates device-side instead of
        # re-transferring 4x[n,20] int32 every dispatch.  _device_src
        # identifies the host build the staged copy mirrors.
        self._device_tables: tuple | None = None
        self._device_src: tuple | None = None
        # Per-thread staging scratch, keyed by padded size: the pipeline
        # runs prepare() on up to pipeline_depth worker threads at once,
        # so buffers are thread-local rather than shared (reuse across
        # waves without a lock).  The dispatch loop's slot threads are
        # long-lived (ISSUE 6), so these pools ARE the preallocated
        # staging-buffer ring: one persistent set per slot.
        self._scratch = threading.local()
        # Challenge-hash memo: k = H(R||A||M) is a pure function of the
        # claim bytes, and fixed-shape padding re-stages the SAME pad
        # claim every wave — memoizing makes pad lanes (and re-verified
        # claims) cost a dict hit instead of a SHA-512 each.  Bounded;
        # cleared wholesale when full (GIL-atomic ops only, so the
        # pipeline's slot threads share it without a lock).
        self._challenge_memo: dict[tuple, bytes] = {}
        # buffer donation decision (resolved lazily, see donate_buffers)
        self._donate: bool | None = None
        # The Pallas VMEM-resident kernel is the fast path on real TPU
        # hardware; the XLA kernel is the portable fallback (CPU tests,
        # sharded-mesh subclass).  use_pallas=None defers autodetection
        # to the first device dispatch — probing the backend in
        # __init__ would initialize JAX in every process that merely
        # CONSTRUCTS a verifier (e.g. small-committee nodes whose
        # batches all route to the CPU hybrid path and that may not be
        # able to claim the device at all).
        self._use_pallas = use_pallas
        if use_pallas is not None:
            self.pad_sizes = PALLAS_PAD_SIZES if use_pallas else PAD_SIZES
        else:
            self.pad_sizes = None  # resolved with use_pallas
        self.min_device_batch = min_device_batch
        self._cpu = None  # lazy CpuVerifier for small batches

    @property
    def use_pallas(self) -> bool:
        if self._use_pallas is None:
            import os

            self._use_pallas = (
                jax.default_backend() == "tpu"
                and not os.environ.get("HOTSTUFF_NO_PALLAS")
            )
        return self._use_pallas

    def _padded_sizes(self) -> tuple[int, ...]:
        if self.pad_sizes is None:
            self.pad_sizes = PALLAS_PAD_SIZES if self.use_pallas else PAD_SIZES
        return self.pad_sizes

    def precompute(self, pubkeys: list[bytes]) -> None:
        """Decompress + negate committee keys ahead of time (epoch
        setup) so no point decompression lands inside a QC verify."""
        for pk in pubkeys:
            self._neg_point(pk)

    def warmup(self, batch: int | None = None) -> None:
        """Compile (or cache-load) the device kernel BEFORE entering the
        consensus hot path.  A cold Mosaic compile of the Pallas kernel
        takes minutes — paid here, once, at node boot, instead of on the
        first QC verify where it would blow through the round timeout.

        ``batch`` is the largest batch the caller expects (the committee
        size: QC/TC verification batches never exceed it) — warming the
        shape THAT batch pads to is the point; the min_device_batch
        floor alone would warm a smaller shape and leave the real QC
        shape cold."""
        from ..crypto import ed25519_ref as ref

        seed = b"\x5a" * 32
        msg = b"hotstuff_tpu verifier warmup"
        pk = ref.public_from_seed(seed)
        sig = ref.sign(seed, msg)
        n = max(batch or 0, self.min_device_batch, 1)  # force device path
        # Warm EVERY pad shape a production batch can land on: QCs are
        # 2f+1 <= committee size, so any pad size at or below the
        # committee's own pad is reachable (e.g. committee 150 pads to
        # 256, but its 101-vote QCs pad to 128 — leaving 128 cold would
        # put a multi-minute Mosaic compile inside the consensus hot
        # path, exactly what this warmup exists to prevent).
        grid = self._padded_sizes()
        ceiling = next((p for p in grid if n <= p), grid[-1])
        floor = max(self.min_device_batch, 1)  # smaller pads never reach
        # the device (the hybrid routing sends those batches to the CPU)
        # ... EXCEPT through the async service's fixed-shape padding
        # (ISSUE 6): a small wave the cost model routes to the device
        # pads UP to the smallest bucket, so that shape must be warm too
        if getattr(self, "supports_wave_padding", False):
            from ..crypto.async_service import resolve_wave_buckets

            # same resolution the service uses: explicit env ladder
            # wins, else this backend's own advertised shapes (the mesh
            # verifier's mesh-multiple buckets, ISSUE 7)
            buckets = resolve_wave_buckets(self)
            if buckets:
                floor = min(floor, buckets[0])
        sizes = [p for p in grid if floor <= p <= ceiling] or [n]
        for size in sizes:
            out = self.verify([msg] * size, [pk] * size, [sig] * size)
            if not out.all():
                raise RuntimeError("verifier warmup produced invalid result")

    def _neg_point(self, pk: bytes):
        hit = self._point_cache.get(pk)
        if hit is None and pk not in self._point_cache:
            p = ref.point_decompress(pk)
            hit = None if p is None else curve.point_to_limbs(ref.point_neg(p))
            with self._table_lock:
                self._point_cache[pk] = hit
                self._tables = None  # stacked table is stale
        return hit

    # staged device-side committee gather; the mesh-sharded subclass
    # overrides the gather so rows land shard-aligned
    device_key_cache = True

    @property
    def donate_buffers(self) -> bool:
        """Donate the per-wave staging arrays to the kernel (ISSUE 6)
        so XLA recycles their device allocations across waves.  On by
        default on accelerator backends; ``HOTSTUFF_DONATE=1/0``
        forces either way (CPU jax has no donation support and warns
        once per shape, so it stays off there unless forced)."""
        if self._donate is None:
            import os

            env = os.environ.get("HOTSTUFF_DONATE", "").strip().lower()
            if env:
                self._donate = env not in ("0", "off", "no", "false")
            else:
                self._donate = jax.default_backend() in ("tpu", "gpu")
        return self._donate

    def _device_build(self, build):
        """The device-resident copy of ``build``'s stacked tables,
        staged on first use after each rebuild.  Idempotent and safe
        without a lock: concurrent stagers both produce a valid copy of
        the same immutable build and last-write-wins."""
        if self._device_src is not build:
            tables, _ = build
            self._device_tables = tuple(jnp.asarray(t) for t in tables)
            self._device_src = build
        return self._device_tables

    def _scratch_for(self, padded: int) -> dict:
        """Preallocated per-thread staging buffers for this pad shape,
        zeroed for reuse (one memset replaces the per-item Python
        writes the old prepare loop did)."""
        pool = getattr(self._scratch, "pool", None)
        if pool is None:
            pool = self._scratch.pool = {}
        bufs = pool.get(padded)
        if bufs is None:
            bufs = pool[padded] = {
                "sig": np.zeros((padded, 64), np.uint8),
                "k": np.zeros((padded, 32), np.uint8),
                "r_sign": np.zeros(padded, np.int32),
                "idxs": np.zeros(padded, np.int64),
            }
        else:
            for a in bufs.values():
                a.fill(0)
        return bufs

    def _rebuild_tables(self):
        """Build (tables, row_index) FULLY in locals, then publish with
        one atomic assignment — this object is shared across the event
        loop and the async verify service's worker thread, so a reader
        must never observe a partially-built index (a torn index maps a
        valid key to the zero row and an honest signature reports
        invalid).  Readers snapshot ``self._tables`` once and use only
        that build."""
        with self._table_lock:
            valid = [
                (pk, pt)
                for pk, pt in self._point_cache.items()
                if pt is not None
            ]
            k = len(valid) + 1
            tables = tuple(
                np.zeros((k, F.NLIMBS), np.int32) for _ in range(4)
            )
            row_index: dict[bytes, int] = {}
            for row, (pk, pt) in enumerate(valid, start=1):
                row_index[pk] = row
                for t, coord in zip(tables, pt):
                    t[row] = coord
            build = (tables, row_index)
            self._tables = build
            self._row_index = row_index
            return build

    def verify(
        self,
        messages: list[bytes],
        pubkeys: list[bytes],
        signatures: list[bytes],
    ) -> np.ndarray:
        """Per-item validity for distinct (message, pk, sig) triples."""
        n = len(messages)
        if not (n == len(pubkeys) == len(signatures)):
            raise ValueError("length mismatch")
        if n == 0:
            return np.zeros(0, bool)
        if n < self.min_device_batch:
            if self._cpu is None:
                from ..crypto.signature import batch_verify_arrays

                self._cpu = batch_verify_arrays
            with _spans.span("host.verify"):
                return np.asarray(self._cpu(messages, pubkeys, signatures))
        return self.verify_device(messages, pubkeys, signatures)

    def verify_device(
        self,
        messages: list[bytes],
        pubkeys: list[bytes],
        signatures: list[bytes],
    ) -> np.ndarray:
        """Per-item validity, forced onto the device kernel regardless of
        ``min_device_batch`` — for callers that already made the
        device-vs-CPU routing decision (the async verify service's
        adaptive dispatcher)."""
        n = len(messages)
        if n == 0:
            return np.zeros(0, bool)
        if n > self._padded_sizes()[-1]:
            # split oversized batches into max-shape chunks
            step = self._padded_sizes()[-1]
            return np.concatenate(
                [
                    self.verify_device(
                        messages[i : i + step],
                        pubkeys[i : i + step],
                        signatures[i : i + step],
                    )
                    for i in range(0, n, step)
                ]
            )

        # the internal dispatch donates its staging arrays when enabled
        # (they are per-wave temporaries); external stage() users call
        # the kernel with donate's default False and may reuse arrays
        donate = self.donate_buffers
        rec = _spans.recorder()
        if rec is None:
            kernel, arrays, valid_host = self.stage(
                messages, pubkeys, signatures
            )
            ok = kernel(*arrays, donate=donate)
            # same fence as the profiled path (ISSUE 5): overlap now
            # happens at the WAVE level — the dispatch pipeline parks
            # this worker thread here (GIL released) while the next
            # wave stages on another thread — so the profiler measures
            # exactly what production runs
            ok = jax.block_until_ready(ok)
            return np.asarray(ok)[:n] & valid_host
        # profiling: split the dispatch into its waterfall stages;
        # structurally identical to the production path above
        with rec.span("prepare"):
            kernel, arrays, valid_host = self.stage(
                messages, pubkeys, signatures
            )
        with rec.span("dispatch"):
            ok = kernel(*arrays, donate=donate)
        with rec.span("device.execute"):
            ok = jax.block_until_ready(ok)
        with rec.span("readback"):
            return np.asarray(ok)[:n] & valid_host

    def verify_packed(self, dig_buf, pk_buf, sig_buf, rows: int) -> np.ndarray:
        """Zero-copy verify over adopted native ingest-arena columns
        (ISSUE 20): the ``*_buf`` objects expose the arena's digest /
        pk / sig column memory (buffer protocol), every one of ``rows``
        rows holding a well-formed claim (real votes + valid pad rows
        the native packer pre-filled).  Staging reads the columns
        through frombuffer views — no per-claim flatten, no ``b"".join``
        — and feeds the same jitted bucket callable as verify_device.
        The arena memory is never written; the caller owns its lifetime
        until this returns."""
        if rows == 0:
            return np.zeros(0, bool)
        dig_v = np.frombuffer(dig_buf, np.uint8).reshape(rows, 32)
        pk_v = np.frombuffer(pk_buf, np.uint8).reshape(rows, 32)
        sig_v = np.frombuffer(sig_buf, np.uint8).reshape(rows, 64)
        if rows > self._padded_sizes()[-1]:
            # oversize wave: materialize rows and chunk via verify_device
            return self.verify_device(
                [r.tobytes() for r in dig_v],
                [r.tobytes() for r in pk_v],
                [r.tobytes() for r in sig_v],
            )
        donate = self.donate_buffers
        rec = _spans.recorder()
        if rec is None:
            valid_host, arrays = self.prepare_packed(dig_v, pk_v, sig_v)
            ok = self._run_kernel(*arrays, donate=donate)
            ok = jax.block_until_ready(ok)
            return np.asarray(ok)[:rows] & valid_host
        with rec.span("prepare"):
            valid_host, arrays = self.prepare_packed(dig_v, pk_v, sig_v)
        with rec.span("dispatch"):
            ok = self._run_kernel(*arrays, donate=donate)
        with rec.span("device.execute"):
            ok = jax.block_until_ready(ok)
        with rec.span("readback"):
            return np.asarray(ok)[:rows] & valid_host

    def prepare_packed(self, dig_v, pk_v, sig_v) -> tuple[np.ndarray, tuple]:
        """``prepare`` over arena column views: signature staging is ONE
        block copy off the column (the wire parser already validated
        lengths, so the malformed-length scan is gone), and pad rows
        (the same claim every wave) hit the challenge memo.  The
        remaining per-row Python — point-cache lookups and the SHA-512
        challenge — needs hashable bytes keys; a native challenge-hash
        column (SHA-512 mod L in wave_pack.cpp) is the noted follow-up
        that would erase it."""
        n = dig_v.shape[0]
        padded = next(p for p in self._padded_sizes() if p >= n)
        bufs = self._scratch_for(padded)
        sig_rows = bufs["sig"]
        k_rows = bufs["k"]
        r_sign = bufs["r_sign"]
        idxs = bufs["idxs"]

        sig_rows[:n] = sig_v  # one vectorized copy straight off the arena
        valid_host = np.ones(n, dtype=bool)

        # s >= L rejection, vectorized — same compare as prepare()
        s_be = sig_rows[:n, :31:-1]
        diff = s_be != _L_BE
        any_diff = diff.any(axis=1)
        first = np.where(any_diff, diff.argmax(axis=1), 0)
        valid_host &= (s_be[np.arange(n), first] < _L_BE[first]) & any_diff

        pk_b = [r.tobytes() for r in pk_v]
        for i in np.flatnonzero(valid_host):
            if pk_b[i] not in self._point_cache:
                self._neg_point(pk_b[i])
        build = self._tables
        if build is None:
            build = self._rebuild_tables()
        tables, row_of = build
        for i in np.flatnonzero(valid_host):
            row = row_of.get(pk_b[i], 0)
            if row:
                idxs[i] = row
            else:
                valid_host[i] = False  # key decompresses to no point

        memo = self._challenge_memo
        for i in np.flatnonzero(valid_host):
            key = (sig_v[i].tobytes(), pk_b[i], dig_v[i].tobytes())
            kb = memo.get(key)
            if kb is None:
                k = ref.verify_challenge(key[0], key[1], key[2])
                kb = k.to_bytes(32, "little")
                if len(memo) >= 8192:
                    memo.clear()
                memo[key] = kb
            k_rows[i] = np.frombuffer(kb, np.uint8)
        bad = ~valid_host
        if bad.any():
            sig_rows[:n][bad] = 0  # zero scalars -> identity lanes
        r_sign[:n] = sig_rows[:n, 31] >> 7

        s_bits = _bytes_to_windows_msb(sig_rows[:, 32:])
        k_bits = _bytes_to_windows_msb(k_rows)
        r_y = _bytes_rows_to_limbs(sig_rows[:, :32])
        if padded > n:
            r_y[n:, 0] = 1

        if self.device_key_cache:
            ax, ay, az, at = self._gather_device_rows(build, idxs)
        else:
            ax, ay, az, at = (t[idxs] for t in tables)

        return valid_host, (
            ax, ay, az, at, s_bits.T, k_bits.T, r_y, r_sign.copy(),
        )

    def stage(self, messages, pubkeys, signatures):
        """(kernel_fn, kernel arrays, host_validity) for this batch —
        the production dispatch point (bench.py uses it to time exactly
        what production dispatches; the mesh-sharded subclass overrides
        ``_run_kernel``).

        NOTE (round 3): a split-scalar kernel variant (each signature as
        two 128-bit half rows, 16 macro steps) lived here through round
        2.  It was DELETED together with its 2^128-point caches, doubled
        base tables and interleave layout: its entire win was avoiding
        the old 256-lane minimum pad, and the kernel is VPU-throughput-
        bound (~linear cost in lanes — scripts/probe_tile_scaling.py),
        so with the 128-lane tile a 64-vote QC at 32 steps x 128 lanes
        costs the same as 16 steps x 256 lanes, without ~600 lines of
        machinery."""
        valid_host, arrays = self.prepare(messages, pubkeys, signatures)
        return self._run_kernel, arrays, valid_host

    def prepare(
        self,
        messages: list[bytes],
        pubkeys: list[bytes],
        signatures: list[bytes],
    ) -> tuple[np.ndarray, tuple]:
        """Host-side batch preparation: decompressed-point lookups,
        challenge hashing, limb/bit decomposition, shape padding —
        vectorized with numpy so prep never outruns the device kernel.
        Returns (host_validity[n], kernel_arrays) where kernel_arrays feed
        ``_run_kernel`` directly.

        Vectorized staging (ISSUE 5): buffers are preallocated at the
        PADDED shape per worker thread and reused across waves, so a
        wave costs one memset + block numpy ops; the only remaining
        per-item Python is key decompression (cached, epoch-static) and
        the SHA-512 challenge hash (no batch API on the host)."""
        n = len(messages)
        padded = next(p for p in self._padded_sizes() if p >= n)
        bufs = self._scratch_for(padded)
        sig_rows = bufs["sig"]
        k_rows = bufs["k"]
        r_sign = bufs["r_sign"]
        idxs = bufs["idxs"]

        # malformed-length rejections (rare; everything else vectorizes)
        valid_host = np.array(
            [
                len(sig) == 64 and len(pk) == 32
                for sig, pk in zip(signatures, pubkeys)
            ],
            dtype=bool,
        )
        if valid_host.all():
            sig_rows[:n] = np.frombuffer(
                b"".join(signatures), np.uint8
            ).reshape(n, 64)
        else:
            for i in np.flatnonzero(valid_host):
                sig_rows[i] = np.frombuffer(signatures[i], np.uint8)

        # s >= L rejection, vectorized: lexicographic compare of each
        # scalar (big-endian view of sig[32:]) against L; rows equal to
        # L have no differing byte and are rejected too
        s_be = sig_rows[:n, :31:-1]
        diff = s_be != _L_BE
        any_diff = diff.any(axis=1)
        first = np.where(any_diff, diff.argmax(axis=1), 0)
        valid_host &= (s_be[np.arange(n), first] < _L_BE[first]) & any_diff

        # committee points: decompress any unseen key once (the cache
        # insert marks the stacked build stale), THEN snapshot one
        # build — it post-dates this batch's inserts, so row_of covers
        # every valid pk here even if another thread rebuilds
        # concurrently.  Index 0 is the zero dummy row: invalid items
        # keep it, their scalars are zeroed below, and the kernel
        # computes the identity while valid_host masks the lane out.
        for i in np.flatnonzero(valid_host):
            if pubkeys[i] not in self._point_cache:
                self._neg_point(pubkeys[i])
        build = self._tables
        if build is None:
            build = self._rebuild_tables()
        tables, row_of = build
        for i in np.flatnonzero(valid_host):
            row = row_of.get(pubkeys[i], 0)
            if row:
                idxs[i] = row
            else:
                valid_host[i] = False  # key decompresses to no point

        # challenge hashes: the irreducible per-item host work —
        # memoized, so fixed-shape pad lanes (same claim every wave)
        # and re-verified claims skip the SHA-512
        memo = self._challenge_memo
        for i in np.flatnonzero(valid_host):
            key = (signatures[i], pubkeys[i], messages[i])
            kb = memo.get(key)
            if kb is None:
                k = ref.verify_challenge(
                    signatures[i], pubkeys[i], messages[i]
                )
                kb = k.to_bytes(32, "little")
                if len(memo) >= 8192:
                    memo.clear()
                memo[key] = kb
            k_rows[i] = np.frombuffer(kb, np.uint8)
        bad = ~valid_host
        if bad.any():
            sig_rows[:n][bad] = 0  # zero scalars -> identity lanes
        r_sign[:n] = sig_rows[:n, 31] >> 7

        # decompositions run at the padded shape directly — pad lanes
        # are all-zero rows (s=0,k=0 -> P=identity, which compresses to
        # y=1,sign=0; r_y gets the matching 'one' rows so pads pass)
        s_bits = _bytes_to_windows_msb(sig_rows[:, 32:])
        k_bits = _bytes_to_windows_msb(k_rows)
        r_y = _bytes_rows_to_limbs(sig_rows[:, :32])
        if padded > n:
            r_y[n:, 0] = 1

        # point rows by row id: device-resident gather when the staged
        # committee table is usable (one [padded] index transfer instead
        # of 4x[padded,20] coordinate rows), host fancy-index otherwise
        if self.device_key_cache:
            ax, ay, az, at = self._gather_device_rows(build, idxs)
        else:
            ax, ay, az, at = (t[idxs] for t in tables)

        return valid_host, (
            ax, ay, az, at, s_bits.T, k_bits.T, r_y, r_sign.copy(),
        )

    def _gather_device_rows(self, build, idxs):
        """Device-side committee-key gather from the staged tables —
        the mesh-sharded verifier overrides this so the gathered rows
        land shard-aligned instead of on one device."""
        return _gather_rows(self._device_build(build), idxs)

    def _run_kernel(
        self, ax, ay, az, at, s_bits, k_bits, r_y, r_sign, donate=False
    ):
        """Device dispatch — overridden by the mesh-sharded verifier.
        ``donate=True`` selects the buffer-donating compilation of the
        same kernel (callers must not reuse the staging arrays after);
        the default keeps external stage() users (bench.py re-dispatches
        the same staged arrays) on the non-consuming variant."""
        if self.use_pallas:
            kernel = (
                _verify_kernel_pallas_donated
                if donate
                else _verify_kernel_pallas
            )
        else:
            kernel = _verify_kernel_donated if donate else _verify_kernel
        return kernel(
            jnp.asarray(ax),
            jnp.asarray(ay),
            jnp.asarray(az),
            jnp.asarray(at),
            jnp.asarray(s_bits),
            jnp.asarray(k_bits),
            jnp.asarray(r_y),
            jnp.asarray(r_sign),
        )

    # -- VerifierBackend protocol (hotstuff_tpu.crypto.service) --------------

    name = "tpu"

    #: the async verify service may pre-pad device waves to fixed
    #: bucket shapes with always-valid filler claims (ISSUE 6) — real
    #: device verifiers opt in; synthetic test hosts never set this
    supports_wave_padding = True

    def verify_many(
        self,
        digests: list[bytes],
        pks: list[bytes],
        sigs: list[bytes],
        aggregate_ok: bool = False,
    ) -> list[bool]:
        # aggregate_ok is irrelevant for ed25519: verification is
        # per-signature on the device regardless
        return [bool(v) for v in self.verify(digests, pks, sigs)]

    def verify_one(self, digest, pk, sig) -> bool:
        return bool(
            self.verify([digest.to_bytes()], [pk.to_bytes()], [sig.to_bytes()])[0]
        )

    def verify_shared_msg(self, digest, votes) -> bool:
        msg = digest.to_bytes()
        out = self.verify(
            [msg] * len(votes),
            [pk.to_bytes() for pk, _ in votes],
            [sig.to_bytes() for _, sig in votes],
        )
        return bool(out.all())
