"""Remote benchmark orchestration over a TPU-VM testbed.

Parity target: reference ``benchmark/benchmark/remote.py:58-298`` — the
Fabric/SSH driver that installs the stack on every instance, uploads
per-node configs, launches clients and nodes in detached remote
sessions, downloads logs, and sweeps (nodes x rate x runs).  Here the
transport is an injectable runner over the ``gcloud compute tpus
tpu-vm ssh/scp`` CLI (see benchmark/instance.py for why), and what gets
installed is this repo's Python/JAX stack instead of a cargo build.

The orchestration logic (command sequences, config fan-out, sweep
shape, results-file discipline ``bench-FAULTS-NODES-RATE-VERIFIER.txt``)
is unit-tested with a recording fake runner — the reference's harness
has no tests at all.
"""

from __future__ import annotations

import os
import shutil
import subprocess

from .instance import TpuVmManager, _default_runner
from .logs import LogParser
from .settings import Settings
from .utils import (
    METRICS_PORT_OFFSET,
    BenchError,
    PathMaker,
    Print,
    save_result,
)


class RemoteBench:
    def __init__(self, settings: Settings, runner=None):
        self.settings = settings
        # NOTE: this attribute must not be called ``run`` — an instance
        # attribute named ``run`` would shadow the public ``run()`` sweep
        # method below and break ``python -m benchmark remote``.
        self._runner = runner if runner is not None else _default_runner
        self.manager = TpuVmManager(settings, runner=self._runner)

    # ---- transport ---------------------------------------------------------

    def _ssh(self, name: str, command: str, timeout: int = 600) -> str:
        s = self.settings
        return self._runner(
            list(s.ssh_command)
            + [name, f"--zone={s.zone}", f"--command={command}"],
            timeout,
        )

    def _upload(self, name: str, local: str, remote: str) -> None:
        s = self.settings
        self._runner(
            list(s.scp_command)
            + [local, f"{name}:{remote}", f"--zone={s.zone}"]
        )

    def _download(self, name: str, remote: str, local: str) -> None:
        s = self.settings
        self._runner(
            list(s.scp_command)
            + [f"{name}:{remote}", local, f"--zone={s.zone}"]
        )

    def _download_dir(self, name: str, remote: str, local: str) -> None:
        """Recursive scp (journal directories hold one ring segment per
        node process, names unknown to the driver)."""
        s = self.settings
        self._runner(
            list(s.scp_command)
            + ["--recurse", f"{name}:{remote}", local, f"--zone={s.zone}"]
        )

    # ---- lifecycle ---------------------------------------------------------

    def install(self) -> None:
        """Clone the repo on every instance (reference remote.py:58-83)."""
        s = self.settings
        # clone into the CONFIGURED directory name — relying on the URL
        # basename matching repo_name breaks the first time they differ
        cmd = (
            f"git clone {s.repo_url} {s.repo_name} || "
            f"(cd {s.repo_name} && git fetch origin)"
        )
        for h in self.manager.hosts():
            Print.info(f"Installing on {h['name']}")
            self._ssh(h["name"], cmd)

    def update(self) -> None:
        """git pull to the configured branch (reference remote.py:117-128)."""
        s = self.settings
        cmd = (
            f"cd {s.repo_name} && git fetch origin && "
            f"git checkout {s.branch} && git reset --hard origin/{s.branch}"
        )
        for h in self.manager.hosts():
            Print.info(f"Updating {h['name']}")
            self._ssh(h["name"], cmd)

    def kill(self) -> None:
        """Stop any running nodes/clients (reference's tmux kill)."""
        for h in self.manager.hosts():
            # bracketed dot so the pattern never matches the remote shell
            # that is executing this very command (pkill -f would SIGTERM
            # it, killing the ssh session before `|| true` runs)
            self._ssh(
                h["name"],
                "pkill -f 'hotstuff_tpu[.]node' || true",
            )

    # ---- one benchmark run -------------------------------------------------

    #: config-to-scenario-epoch margin on the remote rig: covers the
    #: sequential per-host uploads and the detached node boots (the TPU
    #: verifier warms a device kernel) before t=0 windows can open
    REMOTE_BOOT_MARGIN_S = 45.0

    def _config(
        self, hosts: list[dict], nodes: int, chaos_spec: dict | None = None
    ) -> None:
        """Generate keys/committee locally, upload per-node files
        (reference remote.py:130-175).  ``chaos_spec`` (a fault-plane /
        adversary scenario) gets its ``nodes`` map resolved against the
        REAL committee addresses — internal IPs and per-host port
        offsets, not a localhost guess — then is uploaded to every live
        host as ``.faults.json``."""
        import json
        import time

        from hotstuff_tpu.consensus import Committee, Parameters
        from hotstuff_tpu.node.config import (
            Secret,
            write_committee,
            write_parameters,
        )

        keys = [Secret.new() for _ in range(nodes)]
        # round-robin nodes over hosts; co-located nodes (i // len(hosts)
        # > 0) need distinct ports or their listeners collide
        addresses = [
            (
                hosts[i % len(hosts)]["internal_ip"],
                self.settings.consensus_port + i // len(hosts),
            )
            for i in range(nodes)
        ]
        committee = Committee.new(
            [
                (secret.name, 1, addresses[i])
                for i, secret in enumerate(keys)
            ]
        )
        write_committee(committee, PathMaker.committee_file())
        write_parameters(Parameters(), PathMaker.parameters_file())
        for i, secret in enumerate(keys):
            secret.write(PathMaker.key_file(i))
        repo = self.settings.repo_name
        live_hosts = hosts[: min(nodes, len(hosts))]
        if chaos_spec is not None:
            spec = dict(chaos_spec)
            spec["epoch_unix"] = time.time() + self.REMOTE_BOOT_MARGIN_S
            spec["nodes"] = {
                f"{host}:{port}": i
                for i, (host, port) in enumerate(addresses)
            }
            with open(PathMaker.fault_spec_file(), "w") as f:
                json.dump(spec, f, indent=2)
        # shared files once per host; key files once per node
        for host in live_hosts:
            self._upload(host["name"], PathMaker.committee_file(), f"{repo}/")
            self._upload(host["name"], PathMaker.parameters_file(), f"{repo}/")
            if chaos_spec is not None:
                self._upload(
                    host["name"], PathMaker.fault_spec_file(), f"{repo}/"
                )
        for i in range(nodes):
            host = hosts[i % len(hosts)]
            self._upload(host["name"], PathMaker.key_file(i), f"{repo}/")

    def _run_single(
        self,
        hosts: list[dict],
        nodes: int,
        rate: int,
        duration: float,
        faults: int,
        verifier: str,
        journal: bool = False,
        profile: bool = False,
        fault_plane: bool = False,
        adversary: bool = False,
        watch: bool = False,
    ) -> None:
        """Boot clients then nodes in detached remote shells
        (reference remote.py:177-219)."""
        repo = self.settings.repo_name
        # flight recorder / span profiler ride on the node CLI flags so
        # the remote env stays untouched; journal dir is repo-relative
        # (the node cmd below cd's into the repo first)
        tel_flags = ""
        if journal:
            tel_flags += " --journal-dir logs/journals"
        if profile:
            tel_flags += " --profile"
        # chaos/adversary planes: both read the uploaded spec file
        # (repo-relative — the node cmd cd's into the repo first)
        spec_name = os.path.basename(PathMaker.fault_spec_file())
        if fault_plane:
            tel_flags += f" --fault-plane {spec_name}"
        if adversary:
            tel_flags += f" --adversary {spec_name}"
        # Detached-launch shape matters: `mkdir && cd && nohup CMD &`
        # backgrounds the ENTIRE and-list, so the background shell's own
        # un-redirected stdout/stderr keep the ssh channel open until
        # the node exits — every launch "hangs" for the node's lifetime
        # (caught by the localhost transport smoke, scripts/
        # remote_smoke.py).  Background exactly ONE subshell with ALL
        # three fds redirected on it; mkdir runs in a separate command.
        for h in {hosts[i % len(hosts)]["name"] for i in range(nodes)}:
            self._ssh(h, f"mkdir -p {repo}/logs")
        for i in range(nodes - faults):
            host = hosts[i % len(hosts)]
            node_flags = tel_flags
            if watch:
                # health plane + per-node metrics endpoint: the metrics
                # port shares the consensus port's co-location offset so
                # the driver can derive it from the instance map alone
                metrics_port = (
                    self.settings.consensus_port
                    + i // len(hosts)
                    + METRICS_PORT_OFFSET
                )
                node_flags += f" --health --metrics-port {metrics_port}"
            node_cmd = (
                f"( cd {repo} && exec nohup python3 -m hotstuff_tpu.node"
                f" -vv run"
                f" --keys {PathMaker.key_file(i)}"
                f" --committee {PathMaker.committee_file()}"
                f" --store .db_{i}"
                f" --parameters {PathMaker.parameters_file()}"
                f" --verifier {verifier}"
                f"{node_flags}"
                f" ) > {repo}/logs/node-{i}.log 2>&1 < /dev/null &"
            )
            self._ssh(host["name"], node_cmd)
        client_host = hosts[0]
        client_cmd = (
            f"( cd {repo} && exec nohup python3 -m hotstuff_tpu.node.client"
            f" --committee {PathMaker.committee_file()}"
            f" --rate {rate} --duration {duration} --faults {faults}"
            f" ) > {repo}/logs/client.log 2>&1 < /dev/null &"
        )
        self._ssh(client_host["name"], client_cmd)

    def _logs(self, hosts: list[dict], nodes: int, faults: int) -> LogParser:
        """Download every log and parse (reference remote.py:221-235)."""
        # clear stale logs from a previous (possibly larger) run: the
        # parser globs node-*.log, so leftovers would corrupt the summary
        shutil.rmtree(PathMaker.logs_path(), ignore_errors=True)
        os.makedirs(PathMaker.logs_path(), exist_ok=True)
        repo = self.settings.repo_name
        for i in range(nodes - faults):
            host = hosts[i % len(hosts)]
            self._download(
                host["name"],
                f"{repo}/logs/node-{i}.log",
                PathMaker.node_log_file(i),
            )
        self._download(
            hosts[0]["name"],
            f"{repo}/logs/client.log",
            PathMaker.client_log_file(),
        )
        return LogParser.process(PathMaker.logs_path())

    def _journals(self, hosts: list[dict], nodes: int, faults: int) -> int:
        """Pull every live host's journal directory BEFORE the trace
        merge, staging per host (``logs/journals-<host>``) then merging
        the ring segments into ``logs/journals/`` for TraceSet.load.
        Segment filenames embed the sanitized node id, which is unique
        committee-wide, so the merge is a flat copy.  Returns the number
        of segments merged."""
        import glob

        merged_dir = PathMaker.journals_path()
        shutil.rmtree(merged_dir, ignore_errors=True)
        os.makedirs(merged_dir, exist_ok=True)
        repo = self.settings.repo_name
        merged = 0
        live = {hosts[i % len(hosts)]["name"] for i in range(nodes - faults)}
        for name in sorted(live):
            staging = os.path.join(
                PathMaker.logs_path(), f"journals-{name}"
            )
            shutil.rmtree(staging, ignore_errors=True)
            os.makedirs(staging, exist_ok=True)
            try:
                self._download_dir(name, f"{repo}/logs/journals", staging)
            except Exception as e:  # noqa: BLE001 — a host that died
                Print.warn(  # mid-run has no journals; merge the rest
                    f"no journals from {name}: {e}"
                )
                continue
            # scp --recurse lands the dir itself under staging/
            for seg in glob.glob(
                os.path.join(staging, "**", "*.jsonl"), recursive=True
            ):
                shutil.copy(seg, merged_dir)
                merged += 1
        return merged

    def _watch_window(
        self, hosts: list[dict], nodes: int, window_s: float
    ) -> None:
        """Live fleet dashboard over the instance map for the length of
        the measurement window (`remote --watch`).  Targets are the
        instances' EXTERNAL IPs — the driver sits outside the testbed
        network — and every scrape runs under the short watch timeout,
        so an unreachable node shows STALE instead of hanging the
        sweep."""
        from hotstuff_tpu.node.config import Secret

        from .watch import FleetWatcher, run_watch

        targets, keys = [], []
        for i in range(nodes):
            name = Secret.read(PathMaker.key_file(i)).name
            keys.append(name)
            host = hosts[i % len(hosts)]
            targets.append(
                {
                    "index": i,
                    "name": str(name)[:8],
                    "key": name,
                    "host": host["external_ip"] or host["internal_ip"],
                    "port": self.settings.consensus_port
                    + i // len(hosts)
                    + METRICS_PORT_OFFSET,
                }
            )
        order = [str(k)[:8] for k in sorted(keys)]
        watcher = FleetWatcher(targets, order)
        view = run_watch(watcher, duration=window_s, interval=2.0)
        stale = [
            v.get("name", "?") for v in view["nodes"] if v.get("stale")
        ]
        if stale:
            Print.warn(f"STALE at window end: {', '.join(stale)}")
        if watcher.incidents:
            Print.warn(
                f"{len(watcher.incidents)} incident(s) during the window: "
                + ", ".join(
                    f"{i.kind}@{i.node or 'fleet'}"
                    for _, i in watcher.incidents[-10:]
                )
            )

    def run(
        self,
        nodes_list: list[int],
        rate_list: list[int],
        duration: float = 30.0,
        runs: int = 1,
        faults: int = 0,
        verifier: str = "tpu",
        journal: bool = False,
        profile: bool = False,
        fault_plane: str | None = None,
        fault_seed: int = 0,
        watch: bool = False,
    ) -> None:
        """The sweep driver (reference remote.py:237-298).

        ``fault_plane`` is a canned scenario name (hotstuff_tpu/faults/
        scenarios.py — including the byz-* adversary scenarios) or a
        path to a spec JSON; the driver resolves it per committee size,
        uploads it with the configs, and threads ``--fault-plane`` (and
        ``--adversary`` when the spec schedules one) to every node."""
        import json

        hosts = [h for h in self.manager.hosts() if h["state"] == "READY"]
        if not hosts:
            raise BenchError("no READY instances in the testbed")
        import time

        for nodes in nodes_list:
            for rate in rate_list:
                chaos_spec = None
                if fault_plane is not None:
                    if os.path.exists(fault_plane):
                        with open(fault_plane) as f:
                            chaos_spec = json.load(f)
                    else:
                        from hotstuff_tpu.faults.scenarios import build

                        chaos_spec = build(
                            fault_plane, nodes=nodes, seed=fault_seed
                        )
                for attempt in range(runs):
                    Print.heading(
                        f"Remote bench: {nodes} nodes, {rate}/s, "
                        f"run {attempt + 1}/{runs}"
                    )
                    self.kill()
                    self._config(hosts, nodes, chaos_spec=chaos_spec)
                    self._run_single(
                        hosts, nodes, rate, duration, faults, verifier,
                        journal=journal, profile=profile,
                        fault_plane=chaos_spec is not None,
                        adversary=bool(
                            chaos_spec and chaos_spec.get("adversary")
                        ),
                        watch=watch,
                    )
                    if watch:
                        self._watch_window(hosts, nodes, duration + 20)
                    else:
                        time.sleep(duration + 20)
                    self.kill()
                    parser = self._logs(hosts, nodes, faults)
                    summary = parser.result(
                        faults=faults, nodes=nodes, verifier=verifier
                    )
                    if journal:
                        n_segs = self._journals(hosts, nodes, faults)
                        if n_segs:
                            from .traces import TraceSet

                            traces = TraceSet.load(
                                PathMaker.journals_path()
                            )
                            summary += traces.summary()
                            out = traces.export_chrome_trace(
                                PathMaker.trace_file()
                            )
                            Print.info(
                                f"Merged {n_segs} journal segments; "
                                f"trace written to {out}"
                            )
                        else:
                            Print.warn(
                                "journaling requested but no segments "
                                "downloaded"
                            )
                    print(summary)
                    save_result(summary, faults, nodes, rate, verifier,
                                ok=parser.has_window())


__all__ = ["RemoteBench", "TpuVmManager", "Settings", "subprocess"]
