"""Plots: latency vs throughput, TPS vs committee size, robustness.

Parity target: reference ``Ploter`` (benchmark/benchmark/plot.py:16-164):
matplotlib errorbar plots over aggregated series.
"""

from __future__ import annotations

import os

from .aggregate import aggregate
from .utils import PathMaker


def _plt():
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    return plt


def _label(nodes: int, faults: int, verifier: str) -> str:
    return f"{nodes} nodes ({verifier})" + (
        f", {faults} faults" if faults else ""
    )


def _series_by_config(groups: dict, value_fn) -> dict[tuple, list]:
    """{(nodes, faults, verifier): [value_fn(rate, metrics), ...]} over
    the aggregated result groups — the shared group-by of every plot."""
    series: dict[tuple, list] = {}
    for (faults, nodes, rate, verifier), metric in sorted(groups.items()):
        series.setdefault((nodes, faults, verifier), []).append(
            value_fn(rate, metric)
        )
    return series


def plot_latency_vs_throughput(
    groups: dict | None = None,
    out_path: str | None = None,
    reference_overlay: bool = False,
) -> str:
    """One line per (nodes, verifier): consensus latency vs achieved TPS.

    ``reference_overlay=True`` adds the reference's published WAN points
    (benchmark/baseline.py) on log-x so WAN-emulated runs can be
    compared against the reference's latency SHAPE — the ~100x absolute
    throughput gap (10-50 server-class hosts vs this one-core rig) stays
    visible instead of hidden."""
    plt = _plt()
    groups = groups if groups is not None else aggregate()
    os.makedirs(PathMaker.plot_path(), exist_ok=True)
    out_path = out_path or os.path.join(
        PathMaker.plot_path(),
        "latency-vs-throughput-wan.png"
        if reference_overlay
        else "latency-vs-throughput.png",
    )

    series = _series_by_config(
        groups,
        lambda rate, metric: (
            metric.get("consensus_tps", 0.0),
            metric.get("consensus_latency_ms", 0.0),
            metric.get("consensus_latency_ms_stdev", 0.0),
        ),
    )

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for (nodes, faults, verifier), points in sorted(series.items()):
        points.sort()
        xs = [p[0] for p in points]
        ys = [p[1] for p in points]
        es = [p[2] for p in points]
        ax.errorbar(
            xs, ys, yerr=es, marker="o", capsize=3,
            label=_label(nodes, faults, verifier),
        )
    if reference_overlay:
        from .baseline import REFERENCE_WAN_FAULTS, REFERENCE_WAN_POINTS

        for label, tps, lat_ms in REFERENCE_WAN_POINTS:
            ax.scatter([tps], [lat_ms], marker="*", s=120, zorder=5)
            ax.annotate(label, (tps, lat_ms), fontsize=7,
                        xytext=(4, 4), textcoords="offset points")
        for faults, (tps_lo, tps_hi), (lat_lo, lat_hi) in REFERENCE_WAN_FAULTS:
            # the published fault runs are ranges: draw the box
            ax.fill_betweenx(
                [lat_lo, lat_hi], tps_lo, tps_hi, alpha=0.15, zorder=1
            )
            ax.annotate(
                f"ref f={faults} (10 nodes)",
                ((tps_lo * tps_hi) ** 0.5, lat_hi),
                fontsize=7, ha="center",
                xytext=(0, 3), textcoords="offset points",
            )
        ax.set_xscale("log")
    ax.set_xlabel("Throughput (payloads/s)")
    ax.set_ylabel("Consensus latency (ms)")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    plt.close(fig)
    return out_path


def plot_robustness(
    groups: dict | None = None, out_path: str | None = None
) -> str:
    """Achieved TPS vs input rate, one line per (nodes, faults,
    verifier) — the reference's robustness plot (benchmark/plot.py:
    tps-vs-input-rate): throughput should track the input rate until
    saturation and degrade gracefully under crash faults, not
    collapse."""
    plt = _plt()
    groups = groups if groups is not None else aggregate()
    os.makedirs(PathMaker.plot_path(), exist_ok=True)
    out_path = out_path or os.path.join(
        PathMaker.plot_path(), "robustness.png"
    )

    series = _series_by_config(
        groups,
        lambda rate, metric: (rate, metric.get("consensus_tps", 0.0)),
    )

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for (nodes, faults, verifier), points in sorted(series.items()):
        if len(points) < 2:
            continue  # a single rate is not a robustness series
        points.sort()
        ax.plot(
            [p[0] for p in points],
            [p[1] for p in points],
            marker="o",
            label=_label(nodes, faults, verifier),
        )
    lims = ax.get_xlim()
    ax.plot(lims, lims, linestyle=":", color="gray", label="ideal (tps = rate)")
    ax.set_xlim(lims)
    ax.set_xlabel("Input rate (payloads/s)")
    ax.set_ylabel("Consensus TPS (payloads/s)")
    ax.legend(fontsize=8)
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    plt.close(fig)
    return out_path


def plot_tps_vs_committee(
    groups: dict | None = None, out_path: str | None = None
) -> str:
    """Consensus TPS vs committee size, one line per verifier backend."""
    plt = _plt()
    groups = groups if groups is not None else aggregate()
    os.makedirs(PathMaker.plot_path(), exist_ok=True)
    out_path = out_path or os.path.join(
        PathMaker.plot_path(), "tps-vs-committee.png"
    )

    series: dict[str, list] = {}
    for (faults, nodes, rate, verifier), metric in sorted(groups.items()):
        if faults:
            continue
        series.setdefault(verifier, []).append(
            (nodes, metric.get("consensus_tps", 0.0))
        )

    fig, ax = plt.subplots(figsize=(7, 4.5))
    for verifier, points in sorted(series.items()):
        points.sort()
        ax.plot(
            [p[0] for p in points],
            [p[1] for p in points],
            marker="o",
            label=f"verifier={verifier}",
        )
    ax.set_xlabel("Committee size (nodes)")
    ax.set_ylabel("Consensus TPS (payloads/s)")
    ax.legend()
    ax.grid(True, alpha=0.3)
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    plt.close(fig)
    return out_path
