"""Committee-wide safety/liveness invariants for chaos runs.

The checkers are PURE functions over per-node commit observations so
the in-process e2e tests can feed them synthetic or live data directly;
``commits_from_logs`` is the adapter that extracts the observations
from a bench run's node logs (same schema as benchmark/logs.py), and
``chaos_block`` renders the verdict as the ``+ CHAOS`` SUMMARY section.

Safety (must hold under ANY fault schedule):
  - no two nodes commit different blocks at the same round;
  - no single node commits two different blocks at the same round
    (restarted nodes may legitimately RE-commit the same block after a
    crash — only a *different* digest at a seen round is a violation).
  Together with per-lifetime in-order commitment these imply the
  committed chains are prefixes of one another.

Liveness (must hold once the scenario heals):
  - some node commits a NEW round (beyond the pre-heal maximum) within
    ``resume_within_s`` of the last heal edge;
  - the first new round is within ``max_round_gap`` of the pre-heal
    maximum (bounds rounds burned to view-change storms during the
    outage).
"""

from __future__ import annotations

import glob
import math
import os
import re

from .logs import RE_COMMITTED, RE_EPOCH, RE_STATE_ROOT, _ts

# commit observation: (wall-clock seconds, round, block digest)
Commit = tuple[float, int, str]

# state-root observation: (state version, root digest, round)
StateRoot = tuple[int, str, int]

# Adversary-plane activity lines (core/proposer/adversary log contract,
# mirroring the RE_COMMITTED approach: the node's log IS its history).
RE_BYZ_ATTACK = re.compile(
    r"byz (equivocate|forge-qc|withhold|double-vote|flood|shadow-commit"
    r"|reconfig-forge|reconfig-shadow"
    r"|adapt-ambush|adapt-sync|adapt-surf|adapt-snipe"
    r"|sync-withhold|vote-delay)"
)
# Credit-capped flood admission accounting (faults/adversary.py
# ``ingest_flood``): the victim's typed ACK stream, summed per node.
RE_FLOOD_ADMISSION = re.compile(
    r"byz flood admission: accepted (\d+) shed (\d+)"
)
# The epoch-activation observation regex (``Epoch <e> activated at
# round <r>``) is shared with the SUMMARY parser: see logs.RE_EPOCH.
# Honest-side defense lines: rejected certificates / evicted signatures
# (core._handle_timeout, aggregator.QCMaker) and equivocation evidence
# (a second paid digest cell — aggregator._admit_cell).
RE_QC_REJECT = re.compile(
    r"qc reject: invalid certificate|Evicting invalid vote signature"
)
RE_VOTE_CONFLICT = re.compile(r"second digest cell paid by")


def commits_from_logs(logs_dir: str) -> dict[str, list[Commit]]:
    """Per-node committed-block observations from a logs directory.
    A restarted node's log holds both lifetimes (the runner appends)."""
    out: dict[str, list[Commit]] = {}
    for path in sorted(glob.glob(os.path.join(logs_dir, "node-*.log"))):
        name = os.path.basename(path)[: -len(".log")]
        with open(path) as f:
            content = f.read()
        out[name] = [
            (_ts(ts), int(rnd), digest)
            for ts, rnd, digest in RE_COMMITTED.findall(content)
        ]
    return out


def state_roots_from_logs(logs_dir: str) -> dict[str, list[StateRoot]]:
    """Per-node replicated-execution state roots from a logs directory:
    one (version, root, round) observation per applied commit.  A
    snapshot-rejoined node's sequence legitimately skips the versions it
    slept through — agreement is checked per VERSION, not per index."""
    out: dict[str, list[StateRoot]] = {}
    for path in sorted(glob.glob(os.path.join(logs_dir, "node-*.log"))):
        name = os.path.basename(path)[: -len(".log")]
        with open(path) as f:
            content = f.read()
        out[name] = [
            (int(version), root, int(rnd))
            for _ts_, version, root, rnd in RE_STATE_ROOT.findall(content)
        ]
    return out


def check_state_root_agreement(
    roots_by_node: dict[str, list[StateRoot]],
) -> tuple[bool | None, list[str], dict]:
    """Every node that reports a state root at a given version must
    report the SAME root — the replicated execution layer is
    deterministic, so divergence means a node executed (or *reported*,
    under byz shadow-committers) a different history.  A node may
    re-report a version across restarts, but only with the same root.
    Returns (ok, violations, details); ok is ``None`` when no node
    logged any state root (execution layer absent from the run)."""
    violations: list[str] = []
    chosen: dict[int, tuple[str, str]] = {}  # version -> (root, first node)
    observed = 0
    for node in sorted(roots_by_node):
        seen_here: dict[int, str] = {}
        for version, root, _rnd in roots_by_node[node]:
            observed += 1
            prev = seen_here.get(version)
            if prev is not None and prev != root:
                violations.append(
                    f"{node} reported two state roots at version "
                    f"{version}: {prev} vs {root}"
                )
            seen_here[version] = root
            got = chosen.get(version)
            if got is None:
                chosen[version] = (root, node)
            elif got[0] != root:
                violations.append(
                    f"state-root divergence at version {version}: "
                    f"{got[1]} -> {got[0]}, {node} -> {root}"
                )
    details = {
        "versions_compared": len(chosen),
        "max_version": max(chosen) if chosen else 0,
        "nodes_reporting": sum(1 for r in roots_by_node.values() if r),
    }
    if not observed:
        return None, [], details
    return (not violations), violations, details


def epochs_from_logs(logs_dir: str) -> dict[str, list[tuple[int, int]]]:
    """Per-node epoch-activation observations from a logs directory:
    one ``(epoch, activation_round)`` per logged boundary crossing.
    Nodes that boot (or state-sync) straight into an epoch never log a
    crossing for it — agreement is checked over the nodes that DID."""
    out: dict[str, list[tuple[int, int]]] = {}
    for path in sorted(glob.glob(os.path.join(logs_dir, "node-*.log"))):
        name = os.path.basename(path)[: -len(".log")]
        with open(path) as f:
            content = f.read()
        out[name] = [
            (int(epoch), int(rnd))
            for _ts_, epoch, rnd in RE_EPOCH.findall(content)
        ]
    return out


def check_epoch_agreement(
    epochs_by_node: dict[str, list[tuple[int, int]]],
) -> tuple[bool | None, list[str], dict]:
    """Every node that activates a given epoch must activate it at the
    SAME round — the activation point is ``commit_round + margin`` of a
    2-chain-committed reconfiguration, so divergence means a node
    applied (or *reported*, under byz reconfig-shadow) a different epoch
    history.  Re-activating the same epoch across restarts is fine, but
    only at the same round.  Returns (ok, violations, details); ok is
    ``None`` when no node logged any activation (static-committee run).
    """
    violations: list[str] = []
    chosen: dict[int, tuple[int, str]] = {}  # epoch -> (round, first node)
    observed = 0
    for node in sorted(epochs_by_node):
        seen_here: dict[int, int] = {}
        for epoch, rnd in epochs_by_node[node]:
            observed += 1
            prev = seen_here.get(epoch)
            if prev is not None and prev != rnd:
                violations.append(
                    f"{node} activated epoch {epoch} at two rounds: "
                    f"{prev} vs {rnd}"
                )
            seen_here[epoch] = rnd
            got = chosen.get(epoch)
            if got is None:
                chosen[epoch] = (rnd, node)
            elif got[0] != rnd:
                violations.append(
                    f"epoch-activation divergence at epoch {epoch}: "
                    f"{got[1]} -> round {got[0]}, {node} -> round {rnd}"
                )
    details = {
        "epochs_activated": len(chosen),
        "max_epoch": max(chosen) if chosen else 0,
        "nodes_reporting": sum(1 for e in epochs_by_node.values() if e),
    }
    if not observed:
        return None, [], details
    return (not violations), violations, details


def check_handoff_gap(
    commits_by_node: dict[str, list[Commit]],
    epochs_by_node: dict[str, list[tuple[int, int]]],
    bound: int,
    untrusted: set[str] | frozenset[str] = frozenset(),
) -> tuple[bool | None, list[str], dict]:
    """Commits must never stall more than ``bound`` rounds across an
    epoch boundary: for each activation round A (the MODAL value per
    epoch, so a byz shadow reporter cannot move the boundary), the gap
    between the last committed round before A and the first at/after A
    is at most ``bound`` — and a boundary with no commit beyond it at
    all is a stalled handoff.  ``untrusted`` nodes' observations are
    ignored.  Returns (ok, violations, details); ok is ``None`` without
    any observed boundary."""
    from collections import Counter

    activations: dict[int, Counter] = {}
    for node, obs in epochs_by_node.items():
        if node in untrusted:
            continue
        for epoch, rnd in obs:
            activations.setdefault(epoch, Counter())[rnd] += 1
    if not activations:
        return None, [], {}
    rounds = sorted(
        {
            rnd
            for node, commits in commits_by_node.items()
            if node not in untrusted
            for (_t, rnd, _d) in commits
        }
    )
    violations: list[str] = []
    boundaries: list[tuple[int, int, int | None]] = []
    for epoch in sorted(activations):
        boundary = activations[epoch].most_common(1)[0][0]
        before = [r for r in rounds if r < boundary]
        after = [r for r in rounds if r >= boundary]
        if not after:
            boundaries.append((epoch, boundary, None))
            violations.append(
                f"no commit at or after epoch {epoch}'s activation "
                f"round {boundary} — the handoff stalled"
            )
            continue
        # a boundary inside the pre-genesis gap (no commit before it)
        # measures from round 0: the committee had never committed yet
        gap = after[0] - (before[-1] if before else 0)
        boundaries.append((epoch, boundary, gap))
        if gap > bound:
            violations.append(
                f"commit gap {gap} across epoch {epoch}'s boundary "
                f"(round {boundary}) exceeds the handoff bound {bound}"
            )
    details = {
        "boundaries": boundaries,
        "max_gap": max(
            (g for _e, _b, g in boundaries if g is not None), default=None
        ),
        "bound": bound,
    }
    return (not violations), violations, details


def reconfig_render(
    epoch_ok: bool | None,
    epoch_viol: list[str],
    epoch_details: dict,
    hand_ok: bool | None,
    hand_viol: list[str],
    hand_details: dict,
    trusted_epoch: tuple[bool | None, list[str]] | None = None,
) -> str:
    """Render the ``+ RECONFIG`` SUMMARY section: the epoch-agreement
    verdict, the measured handoff gaps per boundary, and (under
    ``quorum_mode: trusted-subset``) the agreement verdict once the
    adversarial epoch histories are discarded."""
    lines = [" + RECONFIG:\n"]
    if epoch_ok is None:
        lines.append(" Epoch agreement: n/a (no epoch activations logged)\n")
    else:
        ed = epoch_details
        lines.append(
            f" Epoch agreement: {'PASS' if epoch_ok else 'FAIL'}"
            f" ({ed.get('epochs_activated', 0)} epoch boundaries,"
            f" {ed.get('nodes_reporting', 0)} nodes,"
            f" max epoch {ed.get('max_epoch', 0)})\n"
        )
        shown = epoch_viol[:8]
        for v in shown:
            lines.append(f"   ! {v}\n")
        if len(epoch_viol) > len(shown):
            lines.append(
                f"   ! ... and {len(epoch_viol) - len(shown)} more "
                "epoch-agreement violations\n"
            )
    if hand_ok is not None:
        gaps = ", ".join(
            f"epoch {e} @ round {b}: gap {'stalled' if g is None else g}"
            for e, b, g in hand_details.get("boundaries", ())
        )
        lines.append(
            f" Handoff gap (bound {hand_details.get('bound')}): "
            f"{'PASS' if hand_ok else 'FAIL'}"
            + (f" ({gaps})" if gaps else "")
            + "\n"
        )
        for v in hand_viol:
            lines.append(f"   ! {v}\n")
    if trusted_epoch is not None:
        t_ok, t_viol = trusted_epoch
        verdict = "n/a" if t_ok is None else ("PASS" if t_ok else "FAIL")
        lines.append(
            f" Trusted-subset epoch agreement (adversaries excluded): "
            f"{verdict}\n"
        )
        for v in t_viol[:8]:
            lines.append(f"   ! {v}\n")
    return "".join(lines)


def byz_activity_from_logs(logs_dir: str) -> dict[str, dict[str, int]]:
    """Per-node Byzantine activity counts from a logs directory: attack
    lines on adversarial nodes, defense lines on honest ones."""
    out: dict[str, dict[str, int]] = {}
    for path in sorted(glob.glob(os.path.join(logs_dir, "node-*.log"))):
        name = os.path.basename(path)[: -len(".log")]
        with open(path) as f:
            content = f.read()
        counts: dict[str, int] = {}
        for policy in RE_BYZ_ATTACK.findall(content):
            counts[policy] = counts.get(policy, 0) + 1
        for accepted, shed in RE_FLOOD_ADMISSION.findall(content):
            counts["flood_accepted"] = (
                counts.get("flood_accepted", 0) + int(accepted)
            )
            counts["flood_shed"] = counts.get("flood_shed", 0) + int(shed)
        qc_rejects = len(RE_QC_REJECT.findall(content))
        if qc_rejects:
            counts["qc_reject"] = qc_rejects
        conflicts = len(RE_VOTE_CONFLICT.findall(content))
        if conflicts:
            counts["vote_conflict"] = conflicts
        out[name] = counts
    return out


def adversaries_from_spec(
    spec: dict, authorities: dict[int, str] | None = None
) -> dict[str, dict]:
    """Map the spec's adversarial node indexes to log-node names with
    their policies and (when the caller can resolve key files)
    authority identities: {"node-0": {"policies": [...], "authority":
    "ab12cd34" | None}}."""
    out: dict[str, dict] = {}
    for rule in spec.get("adversary", ()):
        nodes = rule.get("node", rule.get("nodes", ()))
        if isinstance(nodes, int):
            nodes = (nodes,)
        for idx in nodes:
            idx = int(idx)
            entry = out.setdefault(
                f"node-{idx}",
                {
                    "index": idx,
                    "policies": [],
                    "authority": (authorities or {}).get(idx),
                },
            )
            policy = rule.get("policy", "?")
            if policy not in entry["policies"]:
                entry["policies"].append(policy)
    return out


def attribute_violations(
    violations: list[str], adversaries: dict[str, dict]
) -> list[str]:
    """Annotate each safety violation with the adversarial authorities
    involved: a violation naming an adversarial node (or occurring at
    all while equivocators are live) must point at the equivocating
    authors, not just the conflicting digests."""
    if not adversaries:
        return list(violations)
    out = []
    for v in violations:
        involved = [
            (name, info)
            for name, info in sorted(adversaries.items())
            if re.search(rf"\b{re.escape(name)}\b", v)
        ] or sorted(adversaries.items())
        tags = ", ".join(
            f"{name} ({'/'.join(info['policies'])}"
            + (f", authority {info['authority']}" if info["authority"] else "")
            + ")"
            for name, info in involved
        )
        out.append(f"{v} [adversary: {tags}]")
    return out


def check_safety(
    commits_by_node: dict[str, list[Commit]],
) -> tuple[bool, list[str]]:
    """No conflicting commits at any round, across nodes or within one
    node's (possibly multi-lifetime) history."""
    violations: list[str] = []
    chosen: dict[int, tuple[str, str]] = {}  # round -> (digest, first node)
    for node in sorted(commits_by_node):
        seen_here: dict[int, str] = {}
        for _t, rnd, digest in commits_by_node[node]:
            prev = seen_here.get(rnd)
            if prev is not None and prev != digest:
                violations.append(
                    f"{node} committed two blocks at round {rnd}: "
                    f"{prev} vs {digest}"
                )
            seen_here[rnd] = digest
            got = chosen.get(rnd)
            if got is None:
                chosen[rnd] = (digest, node)
            elif got[0] != digest:
                violations.append(
                    f"conflicting commits at round {rnd}: "
                    f"{got[1]} -> {got[0]}, {node} -> {digest}"
                )
    return (not violations), violations


def trusted_subset_recheck(
    commits_by_node: dict[str, list[Commit]],
    untrusted: set[str] | frozenset[str],
) -> tuple[bool, list[str]]:
    """Re-check safety under TEE-style trusted-subset quorum math
    (arXiv:2512.09409): when attested hardware removes equivocation from
    the fault model, a quorum needs only f+1 of 2f+1 *trusted* replicas,
    and the histories of the untrusted (here: adversarial) nodes are
    discarded before checking consistency.  A full-history FAIL that
    turns into a PASS here demonstrates the attack lives entirely in the
    colluders' reported histories."""
    trusted = {
        node: commits
        for node, commits in commits_by_node.items()
        if node not in untrusted
    }
    return check_safety(trusted)


def check_liveness(
    commits_by_node: dict[str, list[Commit]],
    heal_unix: float,
    resume_within_s: float | None = None,
    max_round_gap: int | None = None,
) -> tuple[bool, list[str], dict]:
    """New rounds commit soon after the last heal edge (wall clock
    ``heal_unix``).  Returns (ok, violations, details) — details carries
    the measured resume latency for the CHAOS block."""
    all_commits = sorted(
        (t, rnd)
        for commits in commits_by_node.values()
        for (t, rnd, _d) in commits
    )
    details: dict = {}
    if not all_commits:
        return False, ["no commits anywhere in the run"], details
    pre = [rnd for t, rnd in all_commits if t <= heal_unix]
    pre_max = max(pre) if pre else -1
    details["pre_heal_max_round"] = pre_max
    post = [
        (t, rnd) for t, rnd in all_commits if t > heal_unix and rnd > pre_max
    ]
    if not post:
        return (
            False,
            [
                "no new rounds committed after the last heal "
                f"(pre-heal max round {pre_max})"
            ],
            details,
        )
    first_t, first_rnd = post[0]
    resumed_after = first_t - heal_unix
    details["resumed_after_s"] = resumed_after
    details["first_new_round"] = first_rnd
    violations: list[str] = []
    if resume_within_s is not None and resumed_after > resume_within_s:
        violations.append(
            f"commits resumed {resumed_after:.1f}s after the heal "
            f"(bound {resume_within_s:.1f}s)"
        )
    if max_round_gap is not None and pre_max >= 0:
        gap = first_rnd - pre_max
        details["round_gap"] = gap
        if gap > max_round_gap:
            violations.append(
                f"round gap across the outage: {gap} (bound {max_round_gap})"
            )
    return (not violations), violations, details


def chaos_block(
    scenario: str,
    seed: int,
    safety_ok: bool,
    safety_violations: list[str],
    liveness_ok: bool | None,
    liveness_violations: list[str],
    details: dict,
    heal_rel: float | None = None,
    state_ok: bool | None = None,
    state_violations: list[str] | tuple = (),
    state_details: dict | None = None,
) -> str:
    """Render the ``+ CHAOS`` SUMMARY section.  ``liveness_ok=None``
    means the scenario never heals (unbounded rule) — liveness is n/a,
    not a failure.  ``state_ok`` is the state-root agreement verdict:
    None with ``state_details=None`` omits the line (caller has no
    execution layer), None WITH details renders n/a (layer present but
    no roots logged)."""
    lines = [
        " + CHAOS:\n",
        f" Scenario: {scenario} (seed {seed})\n",
        f" Safety (no conflicting commits): {'PASS' if safety_ok else 'FAIL'}\n",
    ]
    # a sustained attack (byz-collude) yields one violation per shadow
    # commit — hundreds per run; cap the render, the count tells the story
    shown = safety_violations[:8]
    for v in shown:
        lines.append(f"   ! {v}\n")
    if len(safety_violations) > len(shown):
        lines.append(
            f"   ! ... and {len(safety_violations) - len(shown)} more "
            "conflicting-commit violations\n"
        )
    if state_details is not None:
        if state_ok is None:
            lines.append(
                " State-root agreement: n/a (no state roots logged)\n"
            )
        else:
            sd = state_details
            lines.append(
                " State-root agreement: "
                f"{'PASS' if state_ok else 'FAIL'}"
                f" ({sd.get('versions_compared', 0)} versions,"
                f" {sd.get('nodes_reporting', 0)} nodes,"
                f" max v{sd.get('max_version', 0)})\n"
            )
            s_shown = list(state_violations)[:8]
            for v in s_shown:
                lines.append(f"   ! {v}\n")
            if len(state_violations) > len(s_shown):
                lines.append(
                    f"   ! ... and {len(state_violations) - len(s_shown)} "
                    "more state-root violations\n"
                )
    if liveness_ok is None:
        lines.append(" Liveness: n/a (scenario never heals)\n")
    else:
        detail = ""
        if "resumed_after_s" in details:
            detail = f" (resumed {details['resumed_after_s']:.1f}s after heal"
            if "round_gap" in details:
                detail += f", round gap {details['round_gap']}"
            detail += ")"
        heal_txt = (
            f"heal at t={heal_rel:.1f}s" if heal_rel is not None else "heal"
        )
        lines.append(
            f" Liveness (recovery after {heal_txt}): "
            f"{'PASS' if liveness_ok else 'FAIL'}{detail}\n"
        )
        for v in liveness_violations:
            lines.append(f"   ! {v}\n")
    return "".join(lines)


def byz_block(
    adversaries: dict[str, dict],
    activity: dict[str, dict[str, int]],
    safety_ok: bool,
    trusted_result: tuple[bool, list[str]] | None = None,
    trusted_state_result: tuple[bool | None, list[str]] | None = None,
) -> str:
    """Render the ``+ BYZ`` SUMMARY section: which nodes attacked, with
    what policies and how often; what the honest committee rejected; and
    (under ``quorum_mode: trusted-subset``) the safety AND state-root
    verdicts once the adversarial histories are discarded."""
    lines = [" + BYZ:\n"]
    for name, info in sorted(adversaries.items()):
        who = f" Adversary {name}"
        if info.get("authority"):
            who += f" (authority {info['authority']})"
        who += f": {'/'.join(info['policies'])}"
        attacks = {
            k: v
            for k, v in activity.get(name, {}).items()
            if k not in (
                "qc_reject", "vote_conflict",
                "flood_accepted", "flood_shed",
            )
        }
        if attacks:
            who += " — " + ", ".join(
                f"{k} x{v}" for k, v in sorted(attacks.items())
            )
        lines.append(who + "\n")
        counts = activity.get(name, {})
        if counts.get("flood_accepted") or counts.get("flood_shed"):
            # credit-capped flood: the victim's admission verdict on the
            # attacker's producer batches (shed = the plane held)
            lines.append(
                f"   flood admission at victim: "
                f"accepted {counts.get('flood_accepted', 0)}, "
                f"shed {counts.get('flood_shed', 0)}\n"
            )
    defended = {
        node: counts
        for node, counts in sorted(activity.items())
        if node not in adversaries
        and (counts.get("qc_reject") or counts.get("vote_conflict"))
    }
    for node, counts in defended.items():
        parts = []
        if counts.get("qc_reject"):
            parts.append(f"qc_reject x{counts['qc_reject']}")
        if counts.get("vote_conflict"):
            parts.append(f"vote_conflict x{counts['vote_conflict']}")
        lines.append(f" Honest {node} rejected: {', '.join(parts)}\n")
    lines.append(
        f" Attack contained (full-history safety): "
        f"{'PASS' if safety_ok else 'FAIL'}\n"
    )
    if trusted_result is not None:
        t_ok, t_viol = trusted_result
        lines.append(
            " Trusted-subset quorum (adversaries excluded): "
            f"{'PASS' if t_ok else 'FAIL'}\n"
        )
        for v in t_viol:
            lines.append(f"   ! {v}\n")
    if trusted_state_result is not None:
        ts_ok, ts_viol = trusted_state_result
        verdict = "n/a" if ts_ok is None else ("PASS" if ts_ok else "FAIL")
        lines.append(
            " Trusted-subset state roots (adversaries excluded): "
            f"{verdict}\n"
        )
        for v in ts_viol[:8]:
            lines.append(f"   ! {v}\n")
    return "".join(lines)


def check_run(
    logs_dir: str,
    spec: dict,
    epoch_unix: float,
    authorities: dict[int, str] | None = None,
) -> tuple[bool, str]:
    """Full invariant check for a finished chaos bench run: parse the
    node logs, evaluate both invariants against the scenario spec, and
    return (all_ok, rendered CHAOS block).  When the spec carries an
    ``adversary`` schedule, safety violations are attributed to the
    Byzantine authorities and a ``+ BYZ`` section is appended; the
    full-history verdict still governs the exit status (a successful
    collusion FAILs the run even if the trusted-subset recheck passes)."""
    from hotstuff_tpu.faults.scenarios import last_heal

    commits = commits_from_logs(logs_dir)
    safety_ok, safety_viol = check_safety(commits)
    adversaries = adversaries_from_spec(spec, authorities)
    if adversaries:
        safety_viol = attribute_violations(safety_viol, adversaries)
    # replicated-execution invariant: honest nodes' state roots agree
    # per version.  n/a (no roots logged) never fails a run; a FAIL does
    # — diverging execution is a safety violation even when the commit
    # histories themselves agree.
    roots = state_roots_from_logs(logs_dir)
    state_ok, state_viol, state_details = check_state_root_agreement(roots)
    if adversaries:
        state_viol = attribute_violations(state_viol, adversaries)
    heal_rel = last_heal(spec)
    liveness = spec.get("liveness", {})
    if math.isinf(heal_rel):
        live_ok: bool | None = None
        live_viol: list[str] = []
        details: dict = {}
        block = chaos_block(
            spec.get("name", "custom"), int(spec.get("seed", 0)),
            safety_ok, safety_viol, live_ok, live_viol, details,
            state_ok=state_ok, state_violations=state_viol,
            state_details=state_details,
        )
        all_ok = safety_ok
    else:
        live_ok, live_viol, details = check_liveness(
            commits,
            heal_unix=epoch_unix + heal_rel,
            resume_within_s=liveness.get("resume_within_s"),
            max_round_gap=liveness.get("max_round_gap"),
        )
        block = chaos_block(
            spec.get("name", "custom"), int(spec.get("seed", 0)),
            safety_ok, safety_viol, live_ok, live_viol, details,
            heal_rel=heal_rel,
            state_ok=state_ok, state_violations=state_viol,
            state_details=state_details,
        )
        all_ok = safety_ok and live_ok
    all_ok = all_ok and state_ok is not False
    # live-reconfiguration invariants: every node that crossed an epoch
    # boundary crossed it at the same round, and commits never stalled
    # more than the declared handoff gap across any boundary
    epochs = epochs_from_logs(logs_dir)
    epoch_ok, epoch_viol, epoch_details = check_epoch_agreement(epochs)
    if adversaries:
        epoch_viol = attribute_violations(epoch_viol, adversaries)
    hand_bound = spec.get("handoff_gap_rounds")
    hand_ok: bool | None = None
    hand_viol: list[str] = []
    hand_details: dict = {}
    if hand_bound is not None:
        # boundaries are measured over honest observations only — a byz
        # shadow reporter must not be able to move the measured boundary
        hand_ok, hand_viol, hand_details = check_handoff_gap(
            commits, epochs, int(hand_bound), untrusted=set(adversaries)
        )
    trusted_epoch = None
    if adversaries and spec.get("quorum_mode") == "trusted-subset":
        te_ok, te_viol, _te_details = check_epoch_agreement(
            {n: e for n, e in epochs.items() if n not in adversaries}
        )
        trusted_epoch = (te_ok, te_viol)
    if spec.get("reconfig") or epoch_ok is not None:
        block += reconfig_render(
            epoch_ok, epoch_viol, epoch_details,
            hand_ok, hand_viol, hand_details,
            trusted_epoch=trusted_epoch,
        )
    all_ok = all_ok and epoch_ok is not False and hand_ok is not False
    if adversaries:
        trusted_result = None
        trusted_state_result = None
        if spec.get("quorum_mode") == "trusted-subset":
            trusted_result = trusted_subset_recheck(
                commits, set(adversaries)
            )
            ts_ok, ts_viol, _ts_details = check_state_root_agreement(
                {n: r for n, r in roots.items() if n not in adversaries}
            )
            trusted_state_result = (ts_ok, ts_viol)
        block += byz_block(
            adversaries,
            byz_activity_from_logs(logs_dir),
            safety_ok,
            trusted_result,
            trusted_state_result,
        )
    return all_ok, block


__all__ = [
    "Commit",
    "StateRoot",
    "adversaries_from_spec",
    "attribute_violations",
    "byz_activity_from_logs",
    "byz_block",
    "chaos_block",
    "check_epoch_agreement",
    "check_handoff_gap",
    "check_liveness",
    "check_run",
    "check_safety",
    "check_state_root_agreement",
    "commits_from_logs",
    "epochs_from_logs",
    "reconfig_render",
    "state_roots_from_logs",
    "trusted_subset_recheck",
]
