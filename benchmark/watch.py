"""Fleet watch: the live committee dashboard (`python -m benchmark watch`).

The aggregator half of the health plane (ISSUE 13).  It builds the
committee map from the real key + committee files (the same resolution
the chaos harness uses — never a re-derived port guess), scrapes every
node's ``/delta`` endpoint (``telemetry/exporter.py``) through a
per-node :class:`~hotstuff_tpu.telemetry.health.DeltaDecoder`, and each
tick renders a terminal dashboard:

  per-node round / commit-rate / expected-leader marker / verify
  route-mix / ingest credit / lag-vs-fleet-head columns, a fleet-wide
  commit p50, and the live incident feed.

Fleet-level anomaly detectors run here over the scraped windows — the
pure functions from ``telemetry/health.py`` that need cross-node
visibility: expected-leader stall (attributed to the round-robin leader
of the fleet head round), straggler (round lag, clock-offset aware),
and state-root divergence at the same version.  Node-local detectors
(view-change storm, commit collapse, shed storm) run on the nodes
themselves and surface through the journal / log-line path.

Unreachable nodes never hang the loop: scrapes run with short timeouts
and a node that misses ``STALE_AFTER`` consecutive pulls shows an
explicit ``STALE`` status column until it answers again.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from hotstuff_tpu.node.config import Secret, read_committee
from hotstuff_tpu.telemetry.health import (
    DeltaDecoder,
    Incident,
    Window,
    epoch_skew,
    leader_stall,
    root_divergence,
    straggler,
)

from .utils import METRICS_PORT_OFFSET, PathMaker, Print

#: per-scrape HTTP timeout — a dead node costs one of these per tick,
#: never a hang
SCRAPE_TIMEOUT_S = 0.8

#: consecutive failed scrapes before a node's status column flips STALE
STALE_AFTER = 3

#: columns: (header, width)
_COLUMNS = (
    ("NODE", 8),
    ("ST", 5),
    ("ROUND", 7),
    ("EPOCH", 5),
    ("CMT/S", 7),
    ("LAG", 5),
    ("LDR", 3),
    ("ROUTE d/m/c", 12),
    ("CREDIT", 7),
    ("EGR/S", 8),
    ("AMP", 5),
    ("P50ms", 7),
    ("DOMINANT-STAGE", 15),
)


def _http_get_json(url: str, timeout_s: float = SCRAPE_TIMEOUT_S) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def fleet_targets(max_nodes: int = 1024) -> tuple[list, list]:
    """(targets, leader_order) from the on-disk committee + key files.

    Each target: ``{"index", "name", "key", "host", "port"}`` with
    ``port`` the node's metrics endpoint (consensus port +
    METRICS_PORT_OFFSET, the derivation LocalBench uses).
    ``leader_order`` is the round-robin leader schedule: short names
    sorted by public key, so ``leader_order[round % n]`` is the
    expected leader of ``round``.
    """
    committee = read_committee(PathMaker.committee_file())
    targets = []
    for i in range(max_nodes):
        path = PathMaker.key_file(i)
        if not os.path.exists(path):
            break
        name = Secret.read(path).name
        addr = committee.address(name)
        if addr is None:
            continue  # key file from an older layout — not a member
        targets.append(
            {
                "index": i,
                "name": str(name)[:8],
                "key": name,
                "host": addr[0],
                "port": addr[1] + METRICS_PORT_OFFSET,
            }
        )
    if not targets:
        raise RuntimeError(
            "no committee found: run `python -m benchmark local --health` "
            "(or chaos/load) first so .committee.json/.node_*.json exist"
        )
    order = [
        str(k)[:8] for k in sorted(t["key"] for t in targets)
    ]
    return targets, order


class NodeFeed:
    """One node's scrape state: the delta decoder plus staleness
    tracking.  ``poll`` never raises and never blocks past the scrape
    timeout."""

    def __init__(self, name: str, url: str, opener=None):
        self.name = name
        self.url = url
        self.decoder = DeltaDecoder()
        self.failures = 0
        self._get = opener or _http_get_json

    @property
    def stale(self) -> bool:
        return self.failures >= STALE_AFTER

    def poll(self, timeout_s: float = SCRAPE_TIMEOUT_S) -> dict | None:
        """One ``/delta`` pull; the up-to-date flat state or None.  A
        sequence gap costs one immediate full re-pull (the decoder
        already reset ``since``), not a wrong merge."""
        for _ in range(2):
            try:
                frame = self._get(
                    f"{self.url}/delta?since={self.decoder.since}", timeout_s
                )
            except (OSError, ValueError):
                self.failures += 1
                return None
            state = self.decoder.apply(frame)
            if state is not None:
                self.failures = 0
                return state
        self.failures += 1
        return None


def node_view(name: str, flat: dict) -> dict:
    """Extract one node's dashboard row fields from its flat state."""

    def g(key, default=None):
        return flat.get(f"{name}.{key}", default)

    return {
        "name": name,
        "round": g("metrics.hotstuff_core_round") or g("state.last_round", 0),
        "epoch": g("metrics.hotstuff_core_epoch", 0),
        "commits": g("trace.commits", 0),
        "credit": g("ingest.last_credit", 0),
        "shed": g("ingest.shed_total", 0),
        "version": g("state.version", 0),
        "root": g("state.root", ""),
        # wire flow accounting (ISSUE 19): cumulative egress bytes (the
        # EGR/S column is its window slope) and the node's propose
        # amplification factor (n-1 when every proposal is one broadcast)
        "net_tx": g("flows.tx_bytes", 0),
        "amp": g("flows.amp.propose", 0.0),
        "p50_ms": g(
            "metrics.hotstuff_commit_edge_seconds{edge=propose_to_commit}"
            ".p50_ms",
            g("trace.edges.propose_to_commit.p50_ms", 0.0),
        ),
        "route": tuple(
            g(f"metrics.hotstuff_verify_route{{route={r}}}", 0)
            for r in ("device", "mesh", "cpu")
        ),
        # rolling critical-path attribution the node's HealthMonitor
        # publishes (telemetry.critpath.rolling_attribution): which
        # lifecycle edge currently dominates its commit latency
        "dominant": g("health.dominant_stage", ""),
        "crit_regime": g("health.regime", ""),
        # node-local detector firings the node itself reports (its own
        # HealthMonitor section) — surfaced in the live incident feed
        "alerts": sorted(
            str(v)
            for k, v in flat.items()
            if k.startswith(f"{name}.health.open.")
        ),
    }


class FleetWatcher:
    """Scrape -> window -> detect -> render, one committee-wide tick at
    a time.  ``tick`` is side-effect free beyond the scrapes and its
    internal windows, and ``render`` is a pure function of the returned
    view, so tests drive both with fake openers and fixture clocks."""

    def __init__(
        self,
        targets: list,
        leader_order: list,
        timeout_s: float = 5.0,
        stall_k: float = 3.0,
        opener=None,
        offsets: dict | None = None,
    ):
        self.feeds = [
            NodeFeed(t["name"], f"http://{t['host']}:{t['port']}", opener)
            for t in targets
        ]
        self.leader_order = leader_order
        self.timeout_s = timeout_s
        self.stall_k = stall_k
        # per-node estimated clock offsets (seconds) for the straggler
        # freshness check; live watch has no journal to estimate from,
        # so this defaults to zeros — the remote driver may pass better
        self.offsets = offsets or {}
        span = max(60.0, 4 * stall_k * timeout_s)
        self._w_commits = {f.name: Window(span_s=span) for f in self.feeds}
        # per-node cumulative wire-egress windows (EGR/S column slope)
        self._w_net = {f.name: Window(span_s=span) for f in self.feeds}
        self._last_sample: dict = {}  # node -> (t, view)
        self._pool = ThreadPoolExecutor(max_workers=max(len(self.feeds), 1))
        self.incidents: list = []  # (t, Incident) history
        self._open: set = set()  # (kind, node) currently firing

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    # -- one tick ---------------------------------------------------------

    def tick(self, now: float) -> dict:
        states = list(
            self._pool.map(lambda f: (f, f.poll()), self.feeds)
        )
        views = []
        rounds_by_node: dict = {}
        roots_by_node: dict = {}
        epochs_by_node: dict = {}
        for feed, flat in states:
            if flat is None:
                prev = self._last_sample.get(feed.name)
                view = dict(prev[1]) if prev else {"name": feed.name}
                view["stale"] = feed.stale
                views.append(view)
                continue
            view = node_view(feed.name, flat)
            view["stale"] = False
            self._last_sample[feed.name] = (now, view)
            self._w_commits[feed.name].push(now, float(view["commits"] or 0))
            self._w_net[feed.name].push(now, float(view.get("net_tx") or 0))
            rounds_by_node[feed.name] = (now, float(view["round"] or 0))
            if view["root"]:
                roots_by_node[feed.name] = (
                    int(view["version"] or 0),
                    str(view["root"]),
                )
            if view.get("epoch"):
                epochs_by_node[feed.name] = int(view["epoch"])
            views.append(view)

        head = max(
            (float(v.get("round") or 0) for v in views), default=0.0
        )
        leader = (
            self.leader_order[int(head) % len(self.leader_order)]
            if self.leader_order
            else ""
        )
        fired = self._detect(
            now, leader, rounds_by_node, roots_by_node, views,
            epochs_by_node,
        )
        self._record(now, fired)
        p50s = [
            float(v["p50_ms"])
            for v in views
            if v.get("p50_ms") and not v.get("stale")
        ]
        return {
            "t": now,
            "nodes": views,
            "head": head,
            "leader": leader,
            "fleet_p50_ms": statistics.median(p50s) if p50s else 0.0,
            "incidents": [i for (_, i) in self.incidents[-8:]],
            "open": sorted(self._open),
        }

    #: node-reported kinds keep the severity their detector assigns
    _SEVERITY = {
        "leader_stall": "crit",
        "commit_collapse": "crit",
        "root_divergence": "crit",
        "epoch_skew": "crit",
    }

    def _detect(
        self, now, leader, rounds_by_node, roots_by_node, views,
        epochs_by_node=None,
    ) -> list:
        fired = []
        # incidents the nodes' own monitors hold open (scraped from the
        # snapshot's health section): the node sees its local anomalies
        # — shed storms, its own commit stall — before the fleet can
        for v in views:
            if v.get("stale"):
                continue
            for kind in v.get("alerts") or ():
                fired.append(
                    Incident(
                        kind,
                        v["name"],
                        self._SEVERITY.get(kind, "warn"),
                        "reported by the node's own monitor",
                    )
                )
        if leader and leader in self._w_commits:
            inc = leader_stall(
                self._w_commits[leader].samples(),
                now,
                self.timeout_s,
                k=self.stall_k,
                node=leader,
            )
            if inc:
                fired.append(inc)
        fired.extend(
            straggler(rounds_by_node, self.offsets, now)
        )
        fired.extend(root_divergence(roots_by_node))
        # live-reconfiguration agreement (ISSUE 14): every node's active
        # epoch gauge should match once a boundary has passed — a node
        # stuck behind missed a certified schedule splice
        fired.extend(epoch_skew(epochs_by_node or {}))
        return fired

    def _record(self, now, fired) -> None:
        keys = {(i.kind, i.node) for i in fired}
        for inc in fired:
            if (inc.kind, inc.node) not in self._open:
                self.incidents.append((now, inc))
        self._open = keys


def render(view: dict) -> str:
    """The dashboard frame for one tick's view — pure string building."""
    lines = []
    header = " ".join(h.ljust(w) for h, w in _COLUMNS)
    lines.append(header)
    lines.append("-" * len(header))
    for v in view["nodes"]:
        stale = v.get("stale", True)
        round_ = float(v.get("round") or 0)
        lag = max(view["head"] - round_, 0.0)
        route = v.get("route") or (0, 0, 0)
        cells = (
            v.get("name", "?"),
            "STALE" if stale else "ok",
            f"{round_:.0f}",
            str(int(v.get("epoch") or 0) or "-"),
            _fmt_rate(v),
            f"{lag:.0f}",
            "*" if v.get("name") == view["leader"] else "",
            "/".join(str(int(r or 0)) for r in route),
            str(v.get("credit", "") or 0),
            _fmt_egress(v),
            _fmt_amp(v),
            f"{float(v.get('p50_ms') or 0):.1f}",
            str(v.get("dominant") or "-"),
        )
        lines.append(
            " ".join(str(c).ljust(w) for c, (_, w) in zip(cells, _COLUMNS))
        )
    lines.append(
        f"fleet: head round {view['head']:.0f}, expected leader "
        f"{view['leader'] or '?'}, commit p50 {view['fleet_p50_ms']:.1f} ms"
    )
    if view["open"]:
        lines.append(
            "OPEN INCIDENTS: "
            + ", ".join(f"{k}@{n or 'fleet'}" for k, n in view["open"])
        )
    for inc in view["incidents"]:
        lines.append(
            f"  ! [{inc.severity}] {inc.kind} {inc.node or 'fleet'}: "
            f"{inc.detail}"
        )
    return "\n".join(lines)


def _fmt_rate(v: dict) -> str:
    r = v.get("commit_rate")
    return f"{r:.1f}" if isinstance(r, float) else "-"


def _fmt_egress(v: dict) -> str:
    """Wire egress B/s (window slope over flows.tx_bytes), scaled."""
    r = v.get("egress_rate")
    if not isinstance(r, float):
        return "-"
    if r >= 1e6:
        return f"{r / 1e6:.1f}MB"
    return f"{r / 1e3:.1f}kB"


def _fmt_amp(v: dict) -> str:
    a = v.get("amp")
    return f"{float(a):.1f}" if a else "-"


def run_watch(
    watcher: FleetWatcher,
    duration: float = 0.0,
    interval: float = 1.0,
    once: bool = False,
    out=print,
    clock=time,
) -> dict:
    """The watch loop; returns the final tick's view.  ``duration <= 0``
    means until interrupted."""
    deadline = clock.time() + duration if duration > 0 else None
    view: dict = {"nodes": [], "head": 0.0, "leader": "",
                  "fleet_p50_ms": 0.0, "incidents": [], "open": []}
    try:
        while True:
            t0 = clock.time()
            view = watcher.tick(t0)
            # per-node commit rate for display: window-slope, computed
            # here so tick's view stays raw counters
            for v in view["nodes"]:
                w = watcher._w_commits.get(v.get("name", ""), None)
                samples = w.samples() if w else []
                if len(samples) >= 2:
                    (ta, va), (tb, vb) = samples[0], samples[-1]
                    v["commit_rate"] = (
                        (vb - va) / (tb - ta) if tb > ta else 0.0
                    )
                # wire-egress B/s: same window-slope treatment over the
                # node's cumulative flows.tx_bytes counter
                wn = watcher._w_net.get(v.get("name", ""), None)
                samples = wn.samples() if wn else []
                if len(samples) >= 2:
                    (ta, va), (tb, vb) = samples[0], samples[-1]
                    v["egress_rate"] = (
                        (vb - va) / (tb - ta) if tb > ta else 0.0
                    )
            if out is print and sys.stdout.isatty() and not once:
                print("\x1b[2J\x1b[H", end="")
            out(render(view))
            if once or (deadline is not None and clock.time() >= deadline):
                return view
            clock.sleep(max(0.0, interval - (clock.time() - t0)))
    except KeyboardInterrupt:
        return view
    finally:
        watcher.close()


def task_watch(args) -> None:
    """`python -m benchmark watch` entry point."""
    targets, order = fleet_targets()
    Print.heading(
        f"Watching {len(targets)} committee nodes "
        f"({targets[0]['host']}:{targets[0]['port']}..)"
    )
    watcher = FleetWatcher(
        targets,
        order,
        timeout_s=args.timeout_delay / 1000.0,
        opener=None,
    )
    view = run_watch(
        watcher,
        duration=args.duration,
        interval=args.interval,
        once=args.once,
    )
    if watcher.incidents:
        Print.warn(
            f"{len(watcher.incidents)} incident(s) observed: "
            + ", ".join(
                f"{i.kind}@{i.node or 'fleet'}"
                for _, i in watcher.incidents[-10:]
            )
        )
    else:
        Print.info("no incidents observed")
    return view


__all__ = [
    "METRICS_PORT_OFFSET",
    "SCRAPE_TIMEOUT_S",
    "STALE_AFTER",
    "fleet_targets",
    "NodeFeed",
    "node_view",
    "FleetWatcher",
    "render",
    "run_watch",
    "task_watch",
]
