"""View-change-storm micro-benchmark (BASELINE config 4).

The storm shape: a committee of N = 256 (f = 85) hits a round timeout.
Every correct node then has to process, on its consensus loop:

1. a **timeout flood** — 2f+1 = 171 incoming ``Timeout`` messages, each
   carrying the sender's single signature AND the same 171-vote
   ``high_qc`` (the most expensive repeated check in the protocol;
   the per-core verified-QC memo collapses the n identical embedded-QC
   verifications to one — measured here with and without the memo);
2. **TC verification**, two shapes — the REALISTIC certificate (every
   entry shares one timeout digest, so same-digest grouped aggregation
   applies) and the adversarial worst case (171 DISTINCT digests — the
   full ``verify_many`` multi-pairing; the reference verifies these
   sequentially, consensus/src/messages.rs:305-311).

Backends measured: ed25519-cpu (OpenSSL), ed25519-tpu (the batch
kernel, optional — pass ``--device``), and bls-cpu (aggregate QC =
one pairing equality regardless of committee size; TC = one
random-weight multi-pairing).

Writes a human-readable report and appends to
``results/storm-<N>-<quorum>-<backend>.txt``.
"""

from __future__ import annotations

import time

N_DEFAULT = 256


def _fmt_ms(s: float) -> str:
    return f"{s * 1e3:.1f} ms"


def _ed25519_fixture(n: int, quorum: int):
    """(committee, timeouts, (tc_realistic, tc_worst), high_qc)."""
    from hotstuff_tpu.consensus import QC, TC, Timeout, Vote
    from hotstuff_tpu.consensus.config import Committee
    from hotstuff_tpu.crypto import Digest, Signature, generate_keypair
    from hotstuff_tpu.crypto.signature import Signature as Sig

    seed = b"\x51" * 32
    members = [generate_keypair(seed, i) for i in range(n)]
    committee = Committee.new(
        [(pk, 1, ("127.0.0.1", 40_000 + i)) for i, (pk, _) in enumerate(members)]
    )
    # the storm's shared high_qc: a full-quorum QC for round 9
    block_digest = Digest.of(b"storm high-qc block")
    vote_digest = Vote(hash=block_digest, round=9, author=members[0][0]).digest()
    high_qc = QC(
        hash=block_digest,
        round=9,
        votes=[
            (pk, Sig.new(vote_digest, sk)) for pk, sk in members[:quorum]
        ],
    )
    timeouts = []
    for pk, sk in members[:quorum]:
        t = Timeout(high_qc=high_qc, round=10, author=pk)
        t.signature = Signature.new(t.digest(), sk)
        timeouts.append(t)
    from hotstuff_tpu.consensus.messages import timeout_digest

    # the REALISTIC TC formed from the flood above: every entry carries
    # high_qc_round = 9, so all entries sign the SAME timeout digest
    tc = TC(
        round=10,
        votes=[
            (pk, Signature.new(timeout_digest(10, 9), sk), 9)
            for pk, sk in members[:quorum]
        ],
    )
    # adversarial worst case: DISTINCT per-entry digests (each entry
    # claims its own high_qc_round) — defeats same-digest grouping
    tc_worst = TC(
        round=10,
        votes=[
            (pk, Signature.new(timeout_digest(10, i), sk), i)
            for i, (pk, sk) in enumerate(members[:quorum])
        ],
    )
    return committee, timeouts, (tc, tc_worst), high_qc


def _bls_fixture(n: int, quorum: int):
    from hotstuff_tpu.consensus import QC, TC, Timeout, Vote
    from hotstuff_tpu.consensus.config import Committee
    from hotstuff_tpu.crypto import Digest, Signature
    from hotstuff_tpu.crypto.bls.service import BlsSigningService
    from hotstuff_tpu.crypto.scheme import bls_keygen, bls_pop

    seed = b"\x52" * 32
    members = [bls_keygen(seed, i) for i in range(n)]
    committee = Committee.new(
        [(pk, 1, ("127.0.0.1", 41_000 + i)) for i, (pk, _) in enumerate(members)],
        scheme="bls",
        pops={pk: bls_pop(secret) for pk, secret in members},
    )
    signers = [BlsSigningService(secret) for _, secret in members[:quorum]]
    block_digest = Digest.of(b"storm high-qc block")
    vote_digest = Vote(hash=block_digest, round=9, author=members[0][0]).digest()
    high_qc = QC(
        hash=block_digest,
        round=9,
        votes=[
            (members[i][0], signers[i].sign_sync(vote_digest))
            for i in range(quorum)
        ],
    )
    timeouts = []
    for i in range(quorum):
        t = Timeout(high_qc=high_qc, round=10, author=members[i][0])
        t.signature = signers[i].sign_sync(t.digest())
        timeouts.append(t)
    from hotstuff_tpu.consensus.messages import timeout_digest

    # realistic TC (every entry shares high_qc_round = 9 — same digest)
    tc = TC(
        round=10,
        votes=[
            (members[i][0], signers[i].sign_sync(timeout_digest(10, 9)), 9)
            for i in range(quorum)
        ],
    )
    # adversarial worst case: distinct per-entry digests
    tc_worst = TC(
        round=10,
        votes=[
            (members[i][0], signers[i].sign_sync(timeout_digest(10, i)), i)
            for i in range(quorum)
        ],
    )
    return committee, timeouts, (tc, tc_worst), high_qc


def _measure(committee, timeouts, tc, verifier) -> dict[str, float]:
    out: dict[str, float] = {}
    if hasattr(verifier, "precompute"):
        # epoch setup, exactly like node boot (node/node.py): committee
        # key decode/caching is not storm work
        verifier.precompute([pk.to_bytes() for pk in committee.authorities])
    # 1a. timeout flood WITH the per-core verified-QC memo (product path)
    cache: set = set()
    t0 = time.perf_counter()
    for t in timeouts:
        t.verify(committee, verifier, qc_cache=cache)
    out["flood_memo_s"] = time.perf_counter() - t0
    # 1b. naive flood: every timeout re-verifies the embedded high_qc
    t0 = time.perf_counter()
    for t in timeouts[: max(4, len(timeouts) // 16)]:  # sampled — O(n) QCs
        t.verify(committee, verifier, qc_cache=None)
    sampled = max(4, len(timeouts) // 16)
    out["flood_naive_s"] = (time.perf_counter() - t0) / sampled * len(timeouts)
    # 1c. the burst path (Core._preverify_timeout_burst): per 64-message
    # burst ONE aggregate signature check over the shared timeout
    # digest, then per-timeout stake + memoized-QC checks only
    cache2: set = set()
    t0 = time.perf_counter()
    for start in range(0, len(timeouts), 64):
        chunk = timeouts[start : start + 64]
        ok = verifier.verify_shared_msg(
            chunk[0].digest(), [(t.author, t.signature) for t in chunk]
        )
        assert ok
        for t in chunk:
            t.verify(committee, verifier, qc_cache=cache2, sig_verified=True)
    out["flood_burst_s"] = time.perf_counter() - t0
    # 2. TC verification: realistic (all entries share one timeout
    # digest — same-digest grouping applies) and adversarial worst case
    # (every digest distinct — full multi-pairing)
    tc_real, tc_worst = tc
    t0 = time.perf_counter()
    tc_real.verify(committee, verifier)
    out["tc_verify_s"] = time.perf_counter() - t0
    t0 = time.perf_counter()
    tc_worst.verify(committee, verifier)
    out["tc_worst_verify_s"] = time.perf_counter() - t0
    # 3. the shared high_qc alone (the QC shape at committee scale)
    t0 = time.perf_counter()
    timeouts[0].high_qc.verify(committee, verifier)
    out["qc_verify_s"] = time.perf_counter() - t0
    return out


def _measure_offloop_tc(committee, tc_worst, verifier) -> dict[str, float]:
    """The adversarial TC through the PRODUCTION async claims path
    (VERDICT r3 item 8): the worker-thread offload runs the n+1-Miller-
    loop multi-pairing off the event loop (ctypes releases the GIL), so
    the loop keeps serving timers/messages while the verdict computes.
    Reports the verify wall time AND the worst event-loop stall observed
    by a 5 ms heartbeat during it — the stall, not the wall, is what a
    view change feels."""
    import asyncio

    from hotstuff_tpu.crypto.async_service import AsyncVerifyService

    out: dict[str, float] = {}

    async def run() -> None:
        service = AsyncVerifyService.for_backend(verifier)
        lags: list[float] = []
        stop = asyncio.Event()

        async def heartbeat():
            loop = asyncio.get_running_loop()
            while not stop.is_set():
                t0 = loop.time()
                await asyncio.sleep(0.005)
                lags.append(loop.time() - t0 - 0.005)

        hb = asyncio.ensure_future(heartbeat())
        await asyncio.sleep(0.05)  # heartbeat baseline
        t0 = time.perf_counter()
        verdicts = await service.verify_claims(tc_worst.claims())
        out["offloop_tc_worst_s"] = time.perf_counter() - t0
        assert all(verdicts)
        stop.set()
        await hb
        out["offloop_max_stall_s"] = max(lags) if lags else 0.0
        service.close()

    asyncio.run(run())
    return out


def run_storm(
    nodes: int = N_DEFAULT, device: bool = False, bls: bool = True
) -> dict[str, dict[str, float]]:
    from hotstuff_tpu.crypto.service import CpuVerifier

    quorum = 2 * nodes // 3 + 1
    results: dict[str, dict[str, float]] = {}

    committee, timeouts, tc, _ = _ed25519_fixture(nodes, quorum)
    results["ed25519-cpu"] = _measure(committee, timeouts, tc, CpuVerifier())

    if device:
        from hotstuff_tpu.tpu.ed25519 import BatchVerifier

        # production hybrid routing (node/node.py): single-signature
        # verifies stay on CPU, certificate-sized batches go to the
        # device — forcing min_device_batch=0 here would time the
        # dispatch fixed cost 171x on the flood path, which no node pays
        v = BatchVerifier()
        v.precompute([pk.to_bytes() for pk in committee.authorities])
        v.warmup(batch=quorum)
        results["ed25519-tpu"] = _measure(committee, timeouts, tc, v)

    if bls:
        from hotstuff_tpu.crypto.scheme import make_cpu_verifier

        committee, timeouts, tc, _ = _bls_fixture(nodes, quorum)
        bls_verifier = make_cpu_verifier("bls")
        results["bls-cpu"] = _measure(committee, timeouts, tc, bls_verifier)
        if getattr(bls_verifier, "async_kind", None):
            results["bls-cpu"].update(
                _measure_offloop_tc(committee, tc[1], bls_verifier)
            )
        if device:
            # the opt-in TPU ladder offload for the all-distinct storm
            # (VERDICT r5 item 8): measured honestly next to the host
            # route — on this rig it LOSES (per-op-overhead-bound VPU
            # shape, docs/ROUND5.md), which is why it is opt-in
            from hotstuff_tpu.crypto.scheme import make_device_verifier

            v = make_device_verifier("bls", "tpu")
            v.warmup_storm_offload(quorum)
            # only publish the row when the offload will actually serve
            # this quorum size — a declined offload (e.g. quorum < 16)
            # would silently measure the host route under the
            # offload label
            if v.storm_offload_engaged(quorum):
                results["bls-tpu-storm-offload"] = _measure(
                    committee, timeouts, tc, v
                )
            else:
                print(
                    f" storm offload declined for quorum={quorum} "
                    "(not warmed or below the n>=16 floor); "
                    "bls-tpu-storm-offload row skipped"
                )
    return results


def format_report(nodes: int, results: dict[str, dict[str, float]]) -> str:
    quorum = 2 * nodes // 3 + 1
    lines = [
        "-" * 64,
        " VIEW-CHANGE STORM (BASELINE config 4)",
        f" Committee: {nodes} nodes (f = {(nodes - 1) // 3}), quorum = {quorum}",
        "-" * 64,
    ]
    for backend, m in results.items():
        lines += [
            f" + {backend}:",
            f"   Timeout flood x{quorum} (verified-QC memo): "
            f"{_fmt_ms(m['flood_memo_s'])}",
            f"   Timeout flood x{quorum} (naive, extrapolated): "
            f"{_fmt_ms(m['flood_naive_s'])}",
            f"   Timeout flood x{quorum} (burst aggregate): "
            f"{_fmt_ms(m['flood_burst_s'])}",
            f"   TC verify ({quorum} entries, shared high_qc_round): "
            f"{_fmt_ms(m['tc_verify_s'])}",
            f"   TC verify ({quorum} DISTINCT digests, worst case): "
            f"{_fmt_ms(m['tc_worst_verify_s'])}",
            f"   QC verify ({quorum} votes, shared digest): "
            f"{_fmt_ms(m['qc_verify_s'])}",
        ]
        if "offloop_tc_worst_s" in m:
            lines += [
                f"   TC worst case OFF-LOOP (async claims path): "
                f"{_fmt_ms(m['offloop_tc_worst_s'])} wall, "
                f"max event-loop stall "
                f"{_fmt_ms(m['offloop_max_stall_s'])}",
            ]
    lines += [
        " NOTE: on the development rig every device dispatch includes a",
        " ~100+ ms tunnel round-trip (remote chip); co-located hardware",
        " pays tens of microseconds.  bench.py's device_ms slope metric",
        " isolates the per-batch device time.",
        "-" * 64,
    ]
    return "\n".join(lines)
