"""Result aggregation: group runs by setup, mean +/- stdev, series files.

Parity target: reference ``LogAggregator``
(benchmark/benchmark/aggregate.py:75-174): results files named
``bench-<faults>-<nodes>-<rate>-<verifier>.txt`` are grouped by setup and
summarized into per-metric series usable by plot.py.
"""

from __future__ import annotations

import glob
import os
import re
from statistics import mean, stdev

from .utils import PathMaker

# verifier labels may be hyphenated ("tpu-sharded", "bls-cpu"), so the
# verifier group is [\w-]+? with the optional trailing run index kept
# non-greedy-separable by anchoring it to a pure-digit group.
RE_RESULT = re.compile(
    r"bench-(\d+)-(\d+)-(\d+)-([\w-]+?)(?:-(\d+))?\.txt$"
)
RE_METRICS = {
    "consensus_tps": re.compile(r"Consensus TPS: ([\d.]+)"),
    "consensus_latency_ms": re.compile(r"Consensus latency: ([\d.]+)"),
    "e2e_tps": re.compile(r"End-to-end TPS: ([\d.]+)"),
    "e2e_latency_ms": re.compile(r"End-to-end latency: ([\d.]+)"),
}


def parse_result_file(path: str) -> dict[str, float]:
    with open(path) as f:
        content = f.read()
    out = {}
    for key, regex in RE_METRICS.items():
        values = [float(v) for v in regex.findall(content)]
        if values:
            out[key] = mean(values)
            out[key + "_stdev"] = stdev(values) if len(values) > 1 else 0.0
    return out


def aggregate(results_dir: str | None = None) -> dict[tuple, dict[str, float]]:
    """{(faults, nodes, rate, verifier): metrics} across all result files."""
    results_dir = results_dir or PathMaker.results_path()
    out: dict[tuple, dict[str, float]] = {}
    for path in sorted(glob.glob(os.path.join(results_dir, "bench-*.txt"))):
        m = RE_RESULT.search(os.path.basename(path))
        if not m:
            continue
        key = (int(m.group(1)), int(m.group(2)), int(m.group(3)), m.group(4))
        out[key] = parse_result_file(path)
    return out


def _fmt(metric: dict[str, float], key: str, suffix: str = "") -> str:
    """An absent metric prints n/a — a 0 fallback would read as a (great)
    measurement (e.g. every run in a file reporting e2e latency 'n/a')."""
    value = metric.get(key)
    if value is None:
        return "n/a"
    return f"{value:.0f}{suffix}"


def print_summary(groups: dict[tuple, dict[str, float]]) -> None:
    header = (
        f"{'faults':>6} {'nodes':>6} {'rate':>8} {'verifier':>10} "
        f"{'cons tps':>9} {'cons lat':>9} {'e2e tps':>9} {'e2e lat':>9}"
    )
    print(header)
    print("-" * len(header))
    for (faults, nodes, rate, verifier), metric in sorted(groups.items()):
        print(
            f"{faults:>6} {nodes:>6} {rate:>8} {verifier:>10} "
            f"{_fmt(metric, 'consensus_tps'):>9} "
            f"{_fmt(metric, 'consensus_latency_ms', 'm'):>9} "
            f"{_fmt(metric, 'e2e_tps'):>9} "
            f"{_fmt(metric, 'e2e_latency_ms', 'm'):>9}"
        )
