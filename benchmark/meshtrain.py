"""Multi-chip mesh wave-train bench (ISSUE 7): sustained train sigs/s
of the PRODUCTION dispatch pipeline over the sharded mesh backend, per
mesh size, plus the scaling-efficiency metric perfgate guards.

Why subprocesses: XLA fixes the device count at first jax import, so a
CPU host cannot re-mesh in-process.  Each mesh size runs in a child
``python -m benchmark.meshtrain --child '<spec>'`` whose environment is
set BEFORE jax loads:

- ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (force_virtual
  — CPU hosts; a real multi-chip host runs with its real devices),
- ``HOTSTUFF_MESH_DEVICES=<m>`` — the production mesh-sizing knob the
  node CLI exposes as ``--mesh-devices``,
- ``HOTSTUFF_WAVE_BUCKETS=<batches>`` — bound the warm set to exactly
  the measured train shapes (each child pays ~2 XLA compiles per batch:
  the psum-word warmup kernel + the dispatch-loop stage kernel),
- ``HOTSTUFF_FORCE_DEVICE_ROUTE=1`` — the cost model must not re-route
  the train to the host path mid-measurement.

The child drives ``LazyDeviceVerifier("mesh")`` through the real
``AsyncVerifyService`` (fixed-shape buckets, dispatch-loop slots,
depth-K pipelining — the same tunnel contract production nodes use) and
prints ONE JSON line.  The parent assembles the ``mesh_train`` block:

- ``per_mesh[m].per_batch[b].train_sigs_per_s`` — sustained amortized
  train rate (median-of-reps wall over ``train`` distinct-digest waves),
- ``mesh_scaling_efficiency`` — rate(M) / (M x rate(1)) at the largest
  mesh, best batch (1.0 = perfect linear scale-out; the virtual CPU
  mesh shares one socket, so sub-linear here is expected — the metric
  exists to catch REGRESSIONS in the sharded path, not to prove ICI
  speedup on a laptop).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_MESH_SIZES = (1, 2, 4, 8)
# past-1024 coverage is the point (ISSUE 7): 4096 is the new bucket
DEFAULT_BATCHES = (256, 1024, 4096)
DEFAULT_TRAIN = 4
DEFAULT_REPS = 3
CHILD_TIMEOUT_S = 900.0
VIRTUAL_DEVICES = 8


def _child_env(mesh: int, batches, force_virtual: bool) -> dict:
    env = dict(os.environ)
    if force_virtual:
        kept = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        ]
        kept.append(
            f"--xla_force_host_platform_device_count={VIRTUAL_DEVICES}"
        )
        env["XLA_FLAGS"] = " ".join(kept)
        env.setdefault("JAX_PLATFORMS", "cpu")
    env["HOTSTUFF_MESH_DEVICES"] = str(mesh)
    env["HOTSTUFF_WAVE_BUCKETS"] = ",".join(str(b) for b in batches)
    env["HOTSTUFF_FORCE_DEVICE_ROUTE"] = "1"
    return env


def _last_json_line(text: str) -> dict | None:
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def run_child(spec: dict) -> dict:
    """Runs INSIDE the child process (env already pins mesh size,
    buckets, and the device route): sustained wave trains per batch
    through the production async dispatch pipeline."""
    import asyncio

    from benchmark.profile import make_train_claims
    from hotstuff_tpu.crypto.async_service import (
        AsyncVerifyService,
        eval_claims_sync,
    )
    from hotstuff_tpu.node.node import LazyDeviceVerifier

    train = int(spec.get("train", DEFAULT_TRAIN))
    reps = int(spec.get("reps", DEFAULT_REPS))
    batches = tuple(int(b) for b in spec.get("batches", DEFAULT_BATCHES))

    backend = LazyDeviceVerifier("mesh")
    per_batch: dict = {}
    for n in batches:
        claims, pks = make_train_claims(n, train)
        backend.precompute(pks)
        backend.warmup(batch=n)
        # warm the exact train shape through BOTH device entry points:
        # the sync psum-word path (verify_many) and the dispatch-loop
        # stage kernel the service's pipelined slots actually run —
        # batches are buckets, so no measured wave pays a compile
        assert eval_claims_sync(backend.async_backend, [claims[0]]) == [True]
        backend.dispatch_deadline_s = 60.0

        async def drive() -> tuple[list[float], int]:
            svc = AsyncVerifyService(backend, device=True)
            try:
                assert (await svc.verify_claims([claims[0]])) == [True]
                walls: list[float] = []
                for _ in range(reps):
                    t0 = time.perf_counter()
                    futs = []
                    for claim in claims:
                        futs.append(
                            asyncio.ensure_future(svc.verify_claims([claim]))
                        )
                        await asyncio.sleep(0)
                        while svc._pending:
                            await asyncio.sleep(0)
                    results = await asyncio.gather(*futs)
                    walls.append(time.perf_counter() - t0)
                    assert all(r == [True] for r in results)
                walls.sort()
                return walls, svc.mesh_dispatches
            finally:
                svc.close()

        walls, mesh_dispatches = asyncio.run(drive())
        wall = walls[len(walls) // 2]
        per_batch[str(n)] = {
            "train_sigs_per_s": round(train * n / wall),
            "wave_p50_ms": round(wall * 1e3 / train, 3),
            "mesh_dispatches": mesh_dispatches,
        }

    device = backend._device
    mesh = getattr(device, "mesh", None)
    return {
        "mesh": int(spec.get("mesh", 0)),
        "mesh_devices": int(mesh.devices.size) if mesh is not None else None,
        "train_waves": train,
        "reps": reps,
        "per_batch": per_batch,
        "train_sigs_per_s": max(
            v["train_sigs_per_s"] for v in per_batch.values()
        ),
    }


def run_sharded_child() -> dict:
    """Child body for the virtual-mesh ``sharded_route`` re-measure
    (ISSUE 7 satellite): bench.py's own sharded-route probe, but on the
    forced 8-device virtual mesh so CPU hosts stop reporting
    ``mesh_devices: 1``."""
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    import bench

    msgs, pks, sigs = bench.make_qc_batch(256)
    doc = bench.bench_sharded(msgs, pks, sigs)
    doc["virtual_host_devices"] = VIRTUAL_DEVICES
    return doc


def run_sharded_virtual(timeout_s: float = CHILD_TIMEOUT_S) -> dict | None:
    """Parent-side: run the sharded-route probe on the virtual mesh.
    Returns None on any child failure (the caller keeps its in-process
    measurement)."""
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "benchmark.meshtrain", "--child-sharded"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=_child_env(VIRTUAL_DEVICES, DEFAULT_BATCHES, True),
            cwd=REPO_ROOT,
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if proc.returncode != 0:
        return None
    return _last_json_line(proc.stdout)


def run_mesh_train(
    mesh_sizes=DEFAULT_MESH_SIZES,
    batches=DEFAULT_BATCHES,
    train: int = DEFAULT_TRAIN,
    reps: int = DEFAULT_REPS,
    force_virtual: bool = True,
) -> dict:
    """Parent: one child per mesh size, then the efficiency rollup.

    ``force_virtual=False`` on a real multi-chip host (the children then
    mesh over the real devices via HOTSTUFF_MESH_DEVICES alone)."""
    per_mesh: dict = {}
    errors: dict = {}
    spec_base = {"batches": list(batches), "train": train, "reps": reps}
    for m in mesh_sizes:
        spec = dict(spec_base, mesh=m)
        cmd = [
            sys.executable,
            "-m",
            "benchmark.meshtrain",
            "--child",
            json.dumps(spec),
        ]
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=CHILD_TIMEOUT_S,
                env=_child_env(m, batches, force_virtual),
                cwd=REPO_ROOT,
            )
        except subprocess.TimeoutExpired:
            errors[str(m)] = f"timeout after {CHILD_TIMEOUT_S:.0f}s"
            continue
        doc = _last_json_line(proc.stdout)
        if proc.returncode != 0 or doc is None:
            errors[str(m)] = (
                f"rc={proc.returncode}: {proc.stderr.strip()[-400:]}"
            )
            continue
        per_mesh[str(m)] = doc

    out: dict = {
        "mesh_sizes": list(mesh_sizes),
        "batches": list(batches),
        "train_waves": train,
        "force_virtual": bool(force_virtual),
        "per_mesh": per_mesh,
    }
    if errors:
        out["errors"] = errors

    # efficiency vs the smallest measured mesh (normally 1): best batch,
    # because small batches under-fill large meshes by construction
    base_m = min((int(k) for k in per_mesh), default=None)
    if base_m is not None:
        base = per_mesh[str(base_m)]["per_batch"]
        eff_per_mesh: dict = {}
        for m_str, doc in per_mesh.items():
            scale = int(m_str) / base_m
            effs = [
                v["train_sigs_per_s"]
                / (scale * base[b]["train_sigs_per_s"])
                for b, v in doc["per_batch"].items()
                if base.get(b, {}).get("train_sigs_per_s")
            ]
            if effs:
                eff_per_mesh[m_str] = round(max(effs), 4)
        out["scaling_efficiency_per_mesh"] = eff_per_mesh
        top = str(max(int(k) for k in per_mesh))
        if top in eff_per_mesh and int(top) > base_m:
            out["mesh_scaling_efficiency"] = eff_per_mesh[top]
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="mesh wave-train scaling bench (ISSUE 7)"
    )
    ap.add_argument("--child", default=None, help=argparse.SUPPRESS)
    ap.add_argument(
        "--child-sharded", action="store_true", help=argparse.SUPPRESS
    )
    ap.add_argument("--mesh-sizes", default=None, help="e.g. 1,2,4,8")
    ap.add_argument("--batches", default=None, help="e.g. 256,1024,4096")
    ap.add_argument("--train", type=int, default=DEFAULT_TRAIN)
    ap.add_argument("--reps", type=int, default=DEFAULT_REPS)
    ap.add_argument(
        "--real-devices",
        action="store_true",
        help="mesh over the host's real accelerators instead of the "
        "virtual CPU mesh",
    )
    args = ap.parse_args(argv)

    if args.child is not None:
        print(json.dumps(run_child(json.loads(args.child))))
        return 0
    if args.child_sharded:
        print(json.dumps(run_sharded_child()))
        return 0

    kw: dict = {"train": args.train, "reps": args.reps}
    if args.mesh_sizes:
        kw["mesh_sizes"] = tuple(
            int(x) for x in args.mesh_sizes.split(",") if x
        )
    if args.batches:
        kw["batches"] = tuple(int(x) for x in args.batches.split(",") if x)
    print(json.dumps(run_mesh_train(force_virtual=not args.real_devices, **kw)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
