"""TPU-VM lifecycle management.

Parity target: reference ``benchmark/benchmark/instance.py:18-278``
(boto3 EC2 create/terminate/start/stop/list per region), re-targeted at
Cloud TPU VMs through the ``gcloud`` CLI: no cloud SDK is required in
the image, and every operation is one auditable subprocess command.

All shelling-out goes through an injectable ``runner`` callable so the
orchestration logic is unit-testable without network access (the
reference's boto3 calls are untestable without AWS and indeed have no
tests)."""

from __future__ import annotations

import json
import subprocess

from .settings import Settings
from .utils import BenchError, Print


def _default_runner(cmd: list[str], timeout: int = 600) -> str:
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout
    )
    if proc.returncode != 0:
        raise BenchError(
            f"command {' '.join(cmd)} failed: {proc.stderr.strip()}"
        )
    return proc.stdout


class TpuVmManager:
    """Create / delete / start / stop / list the testbed's TPU VMs."""

    def __init__(self, settings: Settings, runner=None):
        self.settings = settings
        self._runner = runner if runner is not None else _default_runner

    def _name(self, i: int) -> str:
        return f"{self.settings.testbed}-{i}"

    def _base(self) -> list[str]:
        return [
            "gcloud",
            "compute",
            "tpus",
            "tpu-vm",
        ]

    def create_instances(self) -> None:
        s = self.settings
        for i in range(s.instances):
            Print.info(f"Creating {self._name(i)} ({s.accelerator_type})")
            self._runner(
                self._base()
                + [
                    "create",
                    self._name(i),
                    f"--zone={s.zone}",
                    f"--accelerator-type={s.accelerator_type}",
                    f"--version={s.runtime_version}",
                ]
            )

    def terminate_instances(self) -> None:
        for i in range(self.settings.instances):
            Print.info(f"Deleting {self._name(i)}")
            self._runner(
                self._base()
                + [
                    "delete",
                    self._name(i),
                    f"--zone={self.settings.zone}",
                    "--quiet",
                ]
            )

    def start_instances(self) -> None:
        for i in range(self.settings.instances):
            self._runner(
                self._base()
                + ["start", self._name(i), f"--zone={self.settings.zone}"]
            )

    def stop_instances(self) -> None:
        for i in range(self.settings.instances):
            self._runner(
                self._base()
                + ["stop", self._name(i), f"--zone={self.settings.zone}"]
            )

    def hosts(self) -> list[dict]:
        """[{name, internal_ip, external_ip, state}] for the testbed."""
        out = self._runner(
            self._base()
            + [
                "list",
                f"--zone={self.settings.zone}",
                "--format=json",
            ]
        )
        info = []
        for item in json.loads(out or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(self.settings.testbed + "-"):
                continue
            endpoints = item.get("networkEndpoints") or [{}]
            info.append(
                {
                    "name": name,
                    "internal_ip": endpoints[0].get("ipAddress", ""),
                    "external_ip": endpoints[0]
                    .get("accessConfig", {})
                    .get("externalIp", ""),
                    "state": item.get("state", "UNKNOWN"),
                }
            )
        return sorted(info, key=lambda d: d["name"])

    def print_info(self) -> None:
        for h in self.hosts():
            Print.info(
                f"{h['name']}: {h['state']} internal={h['internal_ip']} "
                f"external={h['external_ip']}"
            )
