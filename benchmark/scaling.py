"""Committee-scaling decomposition: protocol cost vs host starvation.

VERDICT r2 weak #4: the 1-core dev rig cannot host >=16 node processes,
so raw committee-size sweeps measure host starvation, not protocol
cost, while the reference publishes 50-node data from one-host-per-node
EC2.  This harness produces the best evidence this environment allows:

- an in-process sweep (one asyncio loop hosting the whole committee —
  OS scheduling excluded) with per-node work accounting
  (utils/workstats.py: signature verifies, crypto wall time, event-loop
  lag — the direct starvation signal);
- a decomposition table: measured TPS, aggregate crypto work, loop lag,
  and the per-(node, payload) protocol cost c = core_seconds /
  (payloads * nodes) — every node processes every block, so ONE core
  hosting n nodes sustains ~1/(c*n) payloads/s while n DEDICATED cores
  (the reference's topology) sustain ~1/c per node, i.e. committee size
  costs latency, not throughput, until the leader's own core saturates;
- the multi-host prediction derived from that cost, printed alongside
  the starved single-core measurements so nobody mistakes one for the
  other.

Output: a table on stdout + ``results/scaling-decomposition.txt``.
"""

from __future__ import annotations

import json
import os
import re
from glob import glob

from .local import LocalBench
from .logs import LogParser
from .utils import PathMaker, Print

RE_WORKSTATS = re.compile(r"\[(?:[^]]*)\] (workstats\.[^ ]+) Work stats: (\{.*\})")
RE_TELEMETRY = re.compile(r"Telemetry snapshot: (\{.*\})")


def scrape_workstats(logs_dir: str) -> list[dict]:
    """Last 'Work stats' JSON per node logger across the node logs."""
    latest: dict[str, dict] = {}
    for path in sorted(glob(os.path.join(logs_dir, "node-*.log"))):
        with open(path) as f:
            for line in f:
                m = RE_WORKSTATS.search(line)
                if m:
                    latest[m.group(1)] = json.loads(m.group(2))
    return list(latest.values())


def scrape_telemetry(logs_dir: str) -> list[dict]:
    """Last 'Telemetry snapshot' document per node across the node logs.
    The snapshot is a strict SUPERSET of the Work stats document (the
    pinned telemetry contract), so callers read the same keys from
    either — this scraper is preferred, scrape_workstats is the
    fallback for old logs (ROADMAP follow-up)."""
    latest: dict[tuple, dict] = {}
    for path in sorted(glob(os.path.join(logs_dir, "node-*.log"))):
        with open(path) as f:
            for line in f:
                m = RE_TELEMETRY.search(line)
                if not m:
                    continue
                try:
                    doc = json.loads(m.group(1))
                except ValueError:
                    continue  # truncated log line mid-write
                latest[(path, doc.get("node"))] = doc
    return list(latest.values())


def run_scaling(
    sizes=(4, 8, 16, 32),
    rate: int = 1_000,
    duration: float = 20.0,
    timeout_delay: int = 5_000,
    verifier: str = "cpu",
) -> str:
    # Telemetry snapshots are the preferred work-accounting source (the
    # superset document); HOTSTUFF_WORK_STATS stays on so the loop-lag
    # probe runs AND old-style lines exist as the scrape fallback.
    os.environ["HOTSTUFF_TELEMETRY"] = "1"
    os.environ["HOTSTUFF_WORK_STATS"] = "1"
    rows = []
    try:
        for n in sizes:
            bench = LocalBench(
                nodes=n,
                rate=rate,
                duration=duration,
                timeout_delay=timeout_delay,
                in_process=True,
                verifier=verifier,
            )
            parser: LogParser = bench.run()
            # prefer the telemetry snapshot document (same keys at top
            # level); fall back cleanly when only Work stats lines exist
            stats = scrape_telemetry(PathMaker.logs_path())
            if not stats:
                stats = scrape_workstats(PathMaker.logs_path())
            tps, window = parser.consensus_throughput()
            lat_s = parser.consensus_latency()
            payloads = parser.committed_payloads()
            verify_sigs = sum(s.get("verify_sigs", 0) for s in stats)
            verify_wall_s = (
                sum(s.get("verify_wall_ms", 0.0) for s in stats) / 1e3
            )
            lag_means = [s.get("loop_lag_mean_ms", 0.0) for s in stats]
            rows.append(
                {
                    "nodes": n,
                    "tps": tps,
                    "latency_ms": lat_s * 1e3,
                    "payloads": payloads,
                    "window_s": window,
                    "verify_sigs": verify_sigs,
                    "verify_wall_s": verify_wall_s,
                    "loop_lag_mean_ms": (
                        sum(lag_means) / len(lag_means) if lag_means else 0.0
                    ),
                    "stats_nodes": len(stats),
                    # dispatch-wave routing split (ISSUE 5): scraped
                    # from the verify-service stats lines, so route
                    # flapping is visible per rate in the SUMMARY
                    "route_waves": dict(parser.route_waves),
                    "pipeline_waits": parser.pipeline_waits,
                    # zero-copy ingest split (ISSUE 20): arena-adopted
                    # waves vs. flatten fallbacks on vote waves
                    "zero_copy_waves": parser.zero_copy_waves,
                    "ingest_fallback_waves": parser.ingest_fallback_waves,
                    # compact-certificate columns (ISSUE 9): last emitted
                    # QC wire size plus how many certificates took the
                    # aggregate one-pairing route
                    "qc_bytes": parser.qc_wire_bytes or 0,
                    "agg_claims": parser.agg_claims,
                    "compact_qcs": parser.compact_qcs,
                    # ingest-plane columns (ISSUE 10): admission sheds
                    # and silent proposer drops, committee-wide — the
                    # second is nonzero only when backpressure failed
                    "ingest_shed": sum(
                        (s.get("ingest") or {}).get("shed_total", 0)
                        for s in stats
                    ),
                    "ingest_drops": sum(
                        (s.get("ingest") or {}).get("drop_newest", 0)
                        for s in stats
                    ),
                    # wire-flow columns (ISSUE 19): committee-wide wire
                    # egress and the median propose-amplification factor
                    # (n-1 when every proposal is one broadcast)
                    "net_tx_bytes": (parser.net_summary() or {}).get(
                        "tx_bytes", 0
                    ),
                    "net_amp_p50": (parser.net_summary() or {}).get(
                        "leader_amp_p50"
                    ),
                    # live-reconfiguration column (ISSUE 14): the newest
                    # epoch the committee activated during the window
                    # (1 = static committee, the sweep's normal state)
                    "epoch": (
                        max(parser.epoch_activations)
                        if parser.epoch_activations
                        else 1
                    ),
                }
            )
    finally:
        os.environ.pop("HOTSTUFF_TELEMETRY", None)
        os.environ.pop("HOTSTUFF_WORK_STATS", None)
    return format_report(rows, rate, duration, verifier=verifier)


def format_report(
    rows: list[dict], rate: int, duration: float, verifier: str = "cpu"
) -> str:
    lines = [
        "COMMITTEE-SCALING DECOMPOSITION (in-process, one core, "
        f"{rate}/s input, {duration:.0f}s, verifier={verifier})",
        "",
        f"{'nodes':>6} {'epoch':>5} {'tps':>7} {'lat ms':>7} {'sigs/s':>8} "
        f"{'crypto s':>9} {'lag ms':>7} {'c us':>7} {'route d/c/p/m':>13} "
        f"{'zc%':>4} {'qc B':>6} {'agg':>5} {'shed':>6} {'dropN':>5} "
        f"{'net MB':>7} {'amp':>5} {'pred 1-core/node':>17}",
    ]
    for r in rows:
        window = max(r["window_s"], 1e-9)
        sig_rate = r["verify_sigs"] / window
        # per-(node, payload) protocol cost: the whole committee shares
        # ONE core in-process, so core-seconds ~= wall window; every
        # node processes every payload's block/QC once
        events = max(r["payloads"] * r["nodes"], 1)
        c_us = window / events * 1e6
        predicted = 1e6 / c_us  # payloads/s with a dedicated core/node
        waves = r.get("route_waves") or {}
        total_waves = sum(waves.values())
        if total_waves:
            route = "/".join(
                f"{100 * waves.get(k, 0) // total_waves}"
                for k in ("device", "cpu", "probe", "mesh")
            )
        else:
            route = "-"
        zc = r.get("zero_copy_waves", 0)
        zc_total = zc + r.get("ingest_fallback_waves", 0)
        zc_txt = f"{100 * zc // zc_total}" if zc_total else "-"
        qc_bytes = r.get("qc_bytes", 0)
        qc_txt = f"{qc_bytes}" if qc_bytes else "-"
        agg_claims = r.get("agg_claims", 0)
        agg_txt = f"{agg_claims}" if agg_claims else "-"
        shed = r.get("ingest_shed", 0)
        shed_txt = f"{shed}" if shed else "-"
        drops = r.get("ingest_drops", 0)
        drops_txt = f"{drops}" if drops else "-"
        net_tx = r.get("net_tx_bytes", 0)
        net_txt = f"{net_tx / 1e6:.1f}" if net_tx else "-"
        amp = r.get("net_amp_p50")
        amp_txt = f"{amp:.1f}" if amp else "-"
        lines.append(
            f"{r['nodes']:>6} {r.get('epoch', 1):>5} "
            f"{r['tps']:>7.0f} {r['latency_ms']:>7.0f} "
            f"{sig_rate:>8.0f} {r['verify_wall_s']:>9.2f} "
            f"{r['loop_lag_mean_ms']:>7.2f} {c_us:>7.0f} {route:>13} "
            f"{zc_txt:>4} {qc_txt:>6} {agg_txt:>5} {shed_txt:>6} {drops_txt:>5} "
            f"{net_txt:>7} {amp_txt:>5} {predicted:>17.0f}"
        )
    lines += [
        "",
        "READING THE TABLE",
    ]
    if verifier != "cpu":
        lines += [
            "- sigs/crypto read 0 under --verifier tpu: the async claims "
            "path runs verification OFF the counted loop (that is the "
            "point); use verifier=cpu for on-loop crypto accounting;"
        ]
    lines += [
        "- tps/lat: the starved single-core measurement (NOT protocol "
        "capability beyond ~8 nodes);",
        "- epoch: the newest committee epoch activated in the window "
        "(1 = no live reconfiguration, the sweep's normal state);",
        "- lag ms: mean event-loop scheduling lag — starvation onset is "
        "visible as lag >> 1 ms;",
        "- c us: measured per-(node, payload) protocol cost = "
        "window / (payloads x nodes) core-microseconds;",
        "- zc%: zero-copy ingest hit rate — vote waves the verify "
        "service adopted straight from a native staging arena as a "
        "share of arena-touching waves (adopted + flatten fallbacks; "
        "'-' for non-native transports or pre-ingest logs);",
        "- qc B / agg: last emitted QC's wire size and certificates "
        "served by the aggregate one-pairing route (BLS compact form: "
        "48 B agg sig + ceil(n/8) B signer bitmap vs n x 144 B vote "
        "lists; '-' for ed25519 vote-list committees);",
        "- shed / dropN: payloads the ingest plane shed with a typed "
        "BUSY reply vs payloads SILENTLY dropped at the full proposer "
        "buffer — dropN must stay '-' whenever admission control is "
        "doing its job (docs/LOAD.md);",
        "- net MB / amp: committee-wide wire egress (flow accounting, "
        "HOTSTUFF_NET) and the median propose-amplification factor — "
        "wire/logical egress bytes, n-1 when every proposal is exactly "
        "one broadcast ('-' with accounting disabled);",
        "- pred: payloads/s one node sustains on a DEDICATED core (the "
        "reference topology, one host per node) = 1/c.  Committee size "
        "multiplies the fleet's total work, not the per-node cost, so "
        "the predicted multi-host TPS holds roughly flat with committee "
        "size until the leader's own core saturates — matching the "
        "reference's flat 10->50-node WAN TPS "
        "(~100k tx/s, benchmark/data/2-chain/results/).",
    ]
    return "\n".join(lines)


def main(sizes, rate, duration, verifier="cpu") -> int:
    report = run_scaling(
        sizes=sizes, rate=rate, duration=duration, verifier=verifier
    )
    print(report)
    os.makedirs(PathMaker.results_path(), exist_ok=True)
    path = os.path.join(PathMaker.results_path(), "scaling-decomposition.txt")
    with open(path, "a") as f:
        f.write(report + "\n\n")
    Print.info(f"Report appended to {path}")
    return 0
