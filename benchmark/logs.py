"""Log parsing — the measurement methodology.

Parity target: reference ``benchmark/benchmark/logs.py:15-225``, with the
log-schema contract CORRECTED for this framework (the reference's regexes
are stale against its own fork — SURVEY.md §2.6). The schema, defined
here and emitted by the framework:

node logs (hotstuff_tpu.consensus.*):
  ``Created block <round> (payloads <d1>,<d2>,...) -> <block_digest>`` (proposer)
  ``Committed block <round> -> <block_digest>``                    (core)
  ``Timeout reached for round <round>``                            (core)
  ``Timeout delay set to <ms> ms``                                 (config echo)
client logs (hotstuff_tpu.node.client):
  ``Transactions rate: <rate> tx/s``
  ``Sending sample payload <digest>``
  ``Transaction rate too high for this client``

Metric definitions (mirroring reference logs.py:147-180):
- consensus TPS: UNIQUE committed payload digests / (last commit -
  first proposal), proposals/commits merged across all node logs taking
  the earliest observation per block (deduplication means a payload
  re-proposed after a view change is counted once);
- consensus latency: proposal->commit per block digest;
- end-to-end TPS: same count over (client start - last commit);
- end-to-end latency: sample payload client-send -> commit of the block
  that contains that payload (payload->block map from Created lines).
"""

from __future__ import annotations

import glob
import os
import re
from collections import Counter
from datetime import datetime
from statistics import mean

from .utils import BenchError

_TS = r"(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{3})Z"

RE_CREATED = re.compile(
    _TS + r".*Created block (\d+) \(payloads (\S*)\) -> (\S+)"
)
RE_COMMITTED = re.compile(_TS + r".*Committed block (\d+) -> (\S+)")
# replicated-execution state root per applied commit (core contract:
# ``State root <version> -> <root> (round <round>)``) — the basis of the
# cross-node state-root agreement invariant (benchmark/invariants.py)
RE_STATE_ROOT = re.compile(
    _TS + r".*State root (\d+) -> (\S+) \(round (\d+)\)"
)
# live-reconfiguration boundary crossing (core contract:
# ``Epoch <epoch> activated at round <round>``) — feeds the SUMMARY's
# epoch-transition lines here and the epoch-agreement invariant
# (benchmark/invariants.py)
RE_EPOCH = re.compile(_TS + r".*Epoch (\d+) activated at round (\d+)")
RE_TIMEOUT = re.compile(_TS + r".*Timeout reached for round (\d+)")
RE_TIMEOUT_DELAY = re.compile(r"Timeout delay set to (\d+) ms")
RE_CLIENT_RATE = re.compile(_TS + r".*Transactions rate: (\d+) tx/s")
RE_CLIENT_SIZE = re.compile(r"Transactions size: (\d+) B")
RE_SAMPLE = re.compile(_TS + r".*Sending sample payload (\S+)")
RE_RATE_HIGH = re.compile(r"rate too high")
# cumulative per-service routing counters (async_service._log_stats);
# the [tag] identifies the service instance so the LAST line per tag is
# its total
RE_VERIFY_STATS = re.compile(
    r"Verify service stats \[(\S+)\]: dispatches=(\d+) device=(\d+) "
    r"(?:cpu=(\d+) probe=(\d+) )?"
    r"device_sigs=(\d+) cpu_sigs=(\d+) deadline_misses=(\d+) "
    r"(?:waits=(\d+) depth=(\d+) )?"
    r"(?:mesh=(\d+) )?"
    r"(?:agg=(\d+) agg_sigs=(\d+) )?"
    r"ewma_ms=([\d.]+)"
    r"(?: zc=(\d+) fb=(\d+))?"
)
# periodic per-node telemetry snapshot (telemetry/exporter.py) — a
# cumulative JSON document superseding 'Work stats:'; keep the LAST
# line per node log
RE_TELEMETRY = re.compile(r"Telemetry snapshot: (\{.*\})")
# health-plane incident transitions (telemetry/health.py HealthMonitor):
# one JSON document per detector open/close, timestamped so the SLO
# burn-rate can be integrated over the run
RE_HEALTH = re.compile(_TS + r".*Health incident: (\{.*\})")
RE_HEALTH_ON = re.compile(r"Health monitor running")


def _ts(s: str) -> float:
    return datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%f").timestamp()


class LogParser:
    def __init__(self, node_logs: list[str], client_logs: list[str]):
        """Args are the log *contents* (one string per file)."""
        if not node_logs:
            raise BenchError("No node logs to parse")
        self.num_node_logs = len(node_logs)

        # merged earliest observation per block digest
        self.proposals: dict[str, float] = {}
        self.commits: dict[str, float] = {}
        self.payload_to_block: dict[str, str] = {}
        self.block_payloads: dict[str, tuple[str, ...]] = {}
        self.block_round: dict[str, int] = {}
        self.timeouts = 0
        self.timeout_delay: int | None = None
        # live-reconfiguration boundary crossings: epoch -> the set of
        # activation rounds nodes reported (honest runs report ONE)
        self.epoch_activations: dict[int, set[int]] = {}

        for content in node_logs:
            for ts, rnd, payloads, block in RE_CREATED.findall(content):
                t = _ts(ts)
                if block not in self.proposals or t < self.proposals[block]:
                    self.proposals[block] = t
                plist = tuple(p for p in payloads.split(",") if p)
                self.block_payloads[block] = plist
                for p in plist:
                    self.payload_to_block[p] = block
                self.block_round[block] = int(rnd)
            for ts, rnd, block in RE_COMMITTED.findall(content):
                t = _ts(ts)
                if block not in self.commits or t < self.commits[block]:
                    self.commits[block] = t
                self.block_round.setdefault(block, int(rnd))
            self.timeouts += len(RE_TIMEOUT.findall(content))
            for _ts_, epoch, rnd in RE_EPOCH.findall(content):
                self.epoch_activations.setdefault(int(epoch), set()).add(
                    int(rnd)
                )
            m = RE_TIMEOUT_DELAY.search(content)
            if m:
                self.timeout_delay = int(m.group(1))

        # verify-service routing split: counters are cumulative per
        # service instance, so keep each tag's LAST line and sum tags.
        # This is the device-routing PROOF for tpu-verifier runs
        # (VERDICT r5 item 1): device_sigs vs cpu_sigs says where
        # claims were actually served.
        # keyed by (log file, tag): tags embed pid+serial, which is
        # unique within a host but can collide across hosts in a remote
        # sweep — the log file disambiguates
        # pre-pipeline logs omit the cpu=/probe=/waits=/depth= fields
        # (optional regex groups come back as '') — treat them as 0
        per_tag: dict[tuple, tuple] = {}
        for log_idx, content in enumerate(node_logs):
            for (
                tag, disp, dev, cpu, probe, dsig, csig, miss, waits,
                depth, mesh, agg, agg_sigs, ewma, zc, fb,
            ) in RE_VERIFY_STATS.findall(content):
                per_tag[(log_idx, tag)] = (
                    int(disp), int(dsig), int(csig), int(miss),
                    float(ewma), int(dev), int(cpu or 0), int(probe or 0),
                    int(waits or 0), int(depth or 1), int(mesh or 0),
                    int(agg or 0), int(agg_sigs or 0),
                    int(zc or 0), int(fb or 0),
                )
        self.device_sigs = sum(v[1] for v in per_tag.values())
        self.cpu_route_sigs = sum(v[2] for v in per_tag.values())
        self.deadline_misses = sum(v[3] for v in per_tag.values())
        self.verify_ewma_ms = (
            max(v[4] for v in per_tag.values()) if per_tag else None
        )
        # dispatch-wave routing split (ISSUE 5): waves by final route,
        # plus depth-cap queue events and the configured pipeline depth.
        # mesh= (ISSUE 7) is a SUBSET of device= (sharded-mesh backend
        # dispatches), so "device" here reports single-device waves only
        # and device+mesh reproduces the raw device= counter.
        _mesh = sum(v[10] for v in per_tag.values())
        self.route_waves = {
            "device": sum(v[5] for v in per_tag.values()) - _mesh,
            "mesh": _mesh,
            "cpu": sum(v[6] for v in per_tag.values()),
            "probe": sum(v[7] for v in per_tag.values()),
        }
        self.pipeline_waits = sum(v[8] for v in per_tag.values())
        self.pipeline_depth = (
            max(v[9] for v in per_tag.values()) if per_tag else None
        )
        # aggregate-certificate route (ISSUE 9): "agg" claims served by
        # ONE pairing over the bitmap-selected key sum instead of a
        # per-signature batch; agg_sigs counts the votes those compact
        # certificates stood in for
        self.agg_claims = sum(v[11] for v in per_tag.values())
        self.agg_claim_sigs = sum(v[12] for v in per_tag.values())
        # zero-copy ingest split (ISSUE 20): waves adopted straight
        # from a native staging arena vs. vote-overlapping waves that
        # fell back to the Python flatten path; pre-ingest logs omit
        # the zc=/fb= suffix and read as 0/0 (hit rate renders as '-')
        self.zero_copy_waves = sum(v[13] for v in per_tag.values())
        self.ingest_fallback_waves = sum(v[14] for v in per_tag.values())

        # telemetry snapshots (cumulative): last document per node log
        import json as _json

        self.telemetry_docs: list[dict] = []
        for content in node_logs:
            matches = RE_TELEMETRY.findall(content)
            if not matches:
                continue
            try:
                self.telemetry_docs.append(_json.loads(matches[-1]))
            except ValueError:
                pass  # truncated log line mid-write

        # health-plane incidents (ISSUE 13): every detector open/close
        # transition with its wall time, plus how many nodes ran the
        # in-process monitor (so a quiet run still renders the block —
        # "detectors on, nothing fired" is the healthy-run proof)
        self.health_nodes = 0
        self.health_events: list[tuple[float, dict]] = []
        for content in node_logs:
            if RE_HEALTH_ON.search(content):
                self.health_nodes += 1
            for ts, blob in RE_HEALTH.findall(content):
                try:
                    doc = _json.loads(blob)
                except ValueError:
                    continue  # truncated log line mid-write
                self.health_events.append((_ts(ts), doc))
        self.health_events.sort(key=lambda e: e[0])

        # wire-level flow accounting (ISSUE 19): the flows section of
        # each node's last snapshot — per-(peer, dir, class) byte
        # ledgers plus the per-class amplification factors.  A doc with
        # {"enabled": False} means the node ran with HOTSTUFF_NET=0:
        # the block renders n/a rather than vanishing, so "accounting
        # off" is never mistaken for "no traffic".
        self.flow_docs: list[dict] = [
            d["flows"]
            for d in self.telemetry_docs
            if isinstance(d.get("flows"), dict)
        ]

        # compact-certificate telemetry (ISSUE 9): the aggregator section
        # records the last emitted QC's wire size (compact = agg sig +
        # signer bitmap, vote-list = n x full votes) and how many
        # certificates took the compact form
        _agg_sections = [
            d.get("aggregator", {}) for d in self.telemetry_docs
        ]
        self.qc_wire_bytes = max(
            (s.get("qc_wire_bytes", 0) for s in _agg_sections), default=0
        ) or None
        self.compact_qcs = sum(
            s.get("compact_qcs_total", 0) for s in _agg_sections
        )
        self.compact_tcs = sum(
            s.get("compact_tcs_total", 0) for s in _agg_sections
        )

        # only blocks whose proposal we saw count toward latency
        self.commits = {
            b: t for b, t in self.commits.items() if b in self.proposals
        }

        self.client_start: float | None = None
        self.input_rate: int | None = None
        self.tx_size: int = 0  # payload body bytes (0 = digest-only)
        self.samples: dict[str, float] = {}  # payload -> send time
        self.rate_warnings = 0
        for content in client_logs:
            m = RE_CLIENT_RATE.search(content)
            if m:
                self.client_start = _ts(m.group(1))
                self.input_rate = int(m.group(2))
            m = RE_CLIENT_SIZE.search(content)
            if m:
                self.tx_size = int(m.group(1))
            for ts, payload in RE_SAMPLE.findall(content):
                self.samples[payload] = _ts(ts)
            self.rate_warnings += len(RE_RATE_HIGH.findall(content))

    @classmethod
    def process(cls, logs_dir: str) -> "LogParser":
        node_logs, client_logs = [], []
        for path in sorted(glob.glob(os.path.join(logs_dir, "node-*.log"))):
            with open(path) as f:
                node_logs.append(f.read())
        for path in sorted(glob.glob(os.path.join(logs_dir, "client*.log"))):
            with open(path) as f:
                client_logs.append(f.read())
        return cls(node_logs, client_logs)

    # ---- metrics (reference logs.py:147-180) -------------------------------

    def committed_payloads(self) -> int:
        """UNIQUE payload digests inside committed blocks (a payload
        re-proposed after a view change is counted once)."""
        unique: set[str] = set()
        for block in self.commits:
            unique.update(self.block_payloads.get(block, ()))
        return len(unique)

    def consensus_throughput(self) -> tuple[float, float]:
        """(unique committed payloads/s, duration s) over the
        proposal->commit window."""
        if not self.commits:
            return 0.0, 0.0
        start = min(self.proposals.values())
        end = max(self.commits.values())
        duration = max(end - start, 1e-9)
        return self.committed_payloads() / duration, duration

    def has_window(self) -> bool:
        """True when the run produced a real measurement window (at least
        one commit) — failed runs must not be appended to results files
        (the aggregator means every block in a file)."""
        return bool(self.commits)

    def consensus_latency(self) -> float:
        """Mean proposal->commit latency (s) over PAYLOAD-CARRYING
        blocks — the reference's population (its latency is per batch
        digest, logs.py:157-159, and every upstream block carries a
        batch).  This framework also creates deliberately EMPTY blocks
        to drive the 2-chain commit of in-flight payloads; an empty
        block's commit lag includes waiting for the producer's next
        burst (~25 ms at 20 bursts/s), which is pacing, not consensus
        work — averaging it in overstated the latency by ~2x (measured
        17.5 ms mean vs 9 ms payload-block p50 at 4 nodes / 1k)."""
        lat = [
            self.commits[b] - self.proposals[b]
            for b in self.commits
            if self.block_payloads.get(b)
        ]
        return mean(lat) if lat else 0.0

    def end_to_end_throughput(self) -> tuple[float, float]:
        if not self.commits or self.client_start is None:
            return 0.0, 0.0
        end = max(self.commits.values())
        duration = max(end - self.client_start, 1e-9)
        return self.committed_payloads() / duration, duration

    def _sample_latencies(self) -> list[float]:
        """Send -> containing-block commit latency (s) per committed
        sample payload."""
        lat = []
        for payload, sent in self.samples.items():
            block = self.payload_to_block.get(payload)
            if block is not None and block in self.commits:
                lat.append(self.commits[block] - sent)
        return lat

    def end_to_end_latency(self) -> float | None:
        """Mean sample-payload send -> containing-block commit latency (s).
        None when no sample payload landed in the window — reporting 0 ms
        for "no data" would read as a (great) measurement."""
        lat = self._sample_latencies()
        return mean(lat) if lat else None

    def end_to_end_latency_percentiles(self) -> tuple[float, float] | None:
        """(p50, p99) over the sample-latency population (s), or None
        without committed samples.  Nearest-rank on the sorted
        latencies: the population is small (one tagged sample per
        burst), so interpolation would manufacture precision the
        samples don't carry."""
        lat = sorted(self._sample_latencies())
        if not lat:
            return None

        def rank(p: float) -> float:
            import math

            return lat[min(len(lat) - 1, math.ceil(p * len(lat)) - 1)]

        return rank(0.50), rank(0.99)

    def commit_round_gap(self) -> tuple[float, int] | None:
        """(mean, max) gap between consecutive COMMITTED rounds, or None
        without >= 2 committed rounds.  A gap of 1 is the steady state;
        larger gaps count the rounds lost to view changes — the
        liveness-cost view the storm benches exist to measure."""
        rounds = sorted(
            {self.block_round[b] for b in self.commits if b in self.block_round}
        )
        if len(rounds) < 2:
            return None
        gaps = [b - a for a, b in zip(rounds, rounds[1:])]
        return mean(gaps), max(gaps)

    def epoch_boundary_gap(self) -> int | None:
        """Max commit-round gap across any observed epoch boundary: for
        each activation round A, first committed round >= A minus last
        committed round < A.  None without an observed boundary (or any
        straddling commits) — the handoff-bound proof line for
        reconfiguration runs."""
        if not self.epoch_activations:
            return None
        rounds = sorted(
            {self.block_round[b] for b in self.commits if b in self.block_round}
        )
        gaps = []
        for acts in self.epoch_activations.values():
            for boundary in acts:
                before = [r for r in rounds if r < boundary]
                after = [r for r in rounds if r >= boundary]
                if before and after:
                    gaps.append(after[0] - before[-1])
        return max(gaps) if gaps else None

    def result(
        self,
        faults: int = 0,
        nodes: int | None = None,
        verifier: str = "cpu",
        extra: str = "",
    ) -> str:
        c_tps, c_dur = self.consensus_throughput()
        e_tps, _ = self.end_to_end_throughput()
        e2e_lat = self.end_to_end_latency()
        e2e_lat_txt = (
            f"{round(e2e_lat * 1000)} ms" if e2e_lat is not None
            else "n/a (no sample payload committed in the window)"
        )
        pcts = self.end_to_end_latency_percentiles()
        e2e_pct_txt = (
            f" End-to-end latency p50/p99:"
            f" {round(pcts[0] * 1000)} / {round(pcts[1] * 1000)} ms\n"
            if pcts is not None
            else ""
        )
        # the latency population is payload-carrying blocks (see
        # consensus_latency): a window with only empty 2-chain-driver
        # commits must print n/a, never a flattering 0 ms
        has_payload_commits = any(
            self.block_payloads.get(b) for b in self.commits
        )
        c_lat_txt = (
            f"{round(self.consensus_latency() * 1000)} ms"
            if has_payload_commits
            else "n/a (no payload-carrying commits)"
        )
        # Byte throughput (reference logs.py:147-169 reports BPS): the
        # committed-payload rate times the measured body size.  Only
        # meaningful when the client sent real bodies (tx_size > 0).
        if self.tx_size:
            c_bps_txt = f" Consensus BPS: {round(c_tps * self.tx_size):,} B/s\n"
            e_bps_txt = f" End-to-end BPS: {round(e_tps * self.tx_size):,} B/s\n"
        else:
            c_bps_txt = " Consensus BPS: n/a (digest-only payloads)\n"
            e_bps_txt = " End-to-end BPS: n/a (digest-only payloads)\n"
        return (
            "\n"
            "-----------------------------------------\n"
            " SUMMARY:\n"
            "-----------------------------------------\n"
            " + CONFIG:\n"
            f" Faults: {faults} node(s)\n"
            f" Committee size: {nodes if nodes is not None else '?'} node(s)\n"
            f" Input rate: {self.input_rate or 0} tx/s\n"
            f" Transaction size: {self.tx_size} B\n"
            f" Verifier backend: {verifier}\n"
            f" Consensus timeout delay: {self.timeout_delay or 0} ms\n"
            f" Execution time: {round(c_dur)} s\n"
            "\n"
            " + RESULTS:\n"
            f" Consensus TPS: {round(c_tps)} payloads/s\n"
            + c_bps_txt
            + f" Consensus latency: {c_lat_txt}\n"
            f" End-to-end TPS: {round(e_tps)} payloads/s\n"
            + e_bps_txt
            + f" End-to-end latency: {e2e_lat_txt}\n"
            + e2e_pct_txt
            + f" Committed blocks: {len(self.commits)}\n"
            f" View-change timeouts: {self.timeouts}\n"
            + self._round_gap_txt()
            + self._epoch_txt()
            + f" Client rate warnings: {self.rate_warnings}\n"
            + self._verify_stats_txt()
            + self._telemetry_breakdown_txt()
            + self._health_txt()
            + self._net_txt()
            + extra
            + "-----------------------------------------\n"
        )

    def _round_gap_txt(self) -> str:
        gap = self.commit_round_gap()
        if gap is None:
            return ""
        gap_mean, gap_max = gap
        return (
            f" Commit round gap: mean {gap_mean:.2f}, max {gap_max}"
            " (1.00 = no rounds lost)\n"
        )

    def _epoch_txt(self) -> str:
        """Epoch-transition lines (only for runs that crossed a live
        reconfiguration boundary): which epochs activated where, and the
        worst commit-round gap across any boundary — the handoff cost
        the reconfig chaos scenarios bound."""
        if not self.epoch_activations:
            return ""
        transitions = ", ".join(
            f"epoch {e} at round"
            f" {'/'.join(str(r) for r in sorted(rounds))}"
            for e, rounds in sorted(self.epoch_activations.items())
        )
        out = (
            f" Epoch transitions: {len(self.epoch_activations)}"
            f" ({transitions})\n"
        )
        gap = self.epoch_boundary_gap()
        if gap is not None:
            out += f" Max commit gap across a boundary: {gap} round(s)\n"
        return out

    def _verify_stats_txt(self) -> str:
        """Routing-split lines (only for runs with async verify services
        — the device-routing proof for tpu-verifier A/Bs)."""
        total = self.device_sigs + self.cpu_route_sigs
        if not total and not self.agg_claims:
            return ""
        pct = 100.0 * self.device_sigs / total if total else 0.0
        ewma = (
            f"{self.verify_ewma_ms:.1f} ms"
            if self.verify_ewma_ms is not None
            else "n/a"
        )
        out = (
            f" Verify sigs device-routed: {self.device_sigs:,} of {total:,}"
            f" ({pct:.0f}%)\n"
            f" Verify deadline misses: {self.deadline_misses}\n"
            f" Verify dispatch EWMA (worst service): {ewma}\n"
        )
        # per-route wave split (ISSUE 5): route flapping shows up here
        # as a device/cpu share that moves across rates
        waves = sum(self.route_waves.values())
        if waves:
            shares = "/".join(
                f"{r} {100.0 * n / waves:.0f}%"
                for r, n in self.route_waves.items()
            )
            depth = (
                f", pipeline depth {self.pipeline_depth}"
                if self.pipeline_depth
                else ""
            )
            out += (
                f" Verify route waves: {shares} of {waves:,}"
                f" (queued {self.pipeline_waits}{depth})\n"
            )
        # zero-copy ingest hit rate (ISSUE 20): of the waves that
        # touched the native staging arenas, how many were adopted
        # without the Python flatten hop
        zc_total = self.zero_copy_waves + self.ingest_fallback_waves
        if zc_total:
            out += (
                f" Verify zero-copy ingest: {self.zero_copy_waves:,} of"
                f" {zc_total:,} vote waves adopted"
                f" ({100.0 * self.zero_copy_waves / zc_total:.0f}%"
                f" hit rate)\n"
            )
        # aggregate-certificate route (ISSUE 9): compact QCs/TCs served
        # by one pairing each instead of per-signature batches
        if self.agg_claims:
            out += (
                f" Verify aggregate certificates: {self.agg_claims:,}"
                f" (standing in for {self.agg_claim_sigs:,} sigs,"
                f" one pairing each)\n"
            )
        if self.qc_wire_bytes:
            form = (
                f"{self.compact_qcs:,} compact QCs emitted"
                if self.compact_qcs
                else "vote-list form"
            )
            out += (
                f" QC wire size (last emitted): {self.qc_wire_bytes:,} B"
                f" ({form})\n"
            )
        return out

    def _health_txt(self) -> str:
        """The ``+ HEALTH`` block (only for runs with the health plane
        on): per-detector incident counts plus the SLO burn — the
        fraction of monitored node-time spent inside an open incident.
        Incidents still open at the end of the log burn until the last
        observed event."""
        if not self.health_nodes and not self.health_events:
            return ""
        lines = [" + HEALTH (anomaly detectors):\n"]
        lines.append(f" Nodes monitored: {self.health_nodes}\n")
        opens: Counter = Counter()
        open_at: dict[tuple[str, str], float] = {}
        spans: list[tuple[tuple[str, str], float, float]] = []
        for t, doc in self.health_events:
            key = (doc.get("node", ""), doc.get("kind", "?"))
            if doc.get("phase") == "open":
                opens[doc.get("kind", "?")] += 1
                open_at.setdefault(key, t)
            elif key in open_at:
                spans.append((key, open_at.pop(key), t))
        if open_at:
            end = max(
                [t for t, _ in self.health_events]
                + list(self.commits.values())
            )
            for key, t0 in open_at.items():
                spans.append((key, t0, end))
        if opens:
            shown = ", ".join(
                f"{kind} x{c}" if c > 1 else kind
                for kind, c in sorted(opens.items())
            )
            lines.append(
                f" Incidents: {sum(opens.values())} ({shown})\n"
            )
            worst = max(spans, key=lambda s: s[2] - s[1], default=None)
            if worst is not None:
                (node, kind), t0, t1 = worst
                lines.append(
                    f" Longest incident: {kind} on {node or '?'}"
                    f" ({t1 - t0:.1f} s)\n"
                )
        else:
            lines.append(" Incidents: 0\n")
        _, c_dur = self.consensus_throughput()
        if c_dur and self.health_nodes:
            burn = sum(t1 - t0 for _, t0, t1 in spans) / (
                c_dur * self.health_nodes
            )
            lines.append(
                f" SLO burn: {100.0 * min(burn, 1.0):.1f}% of monitored"
                " node-time inside an open incident\n"
            )
        return "".join(lines)

    def net_summary(self) -> dict | None:
        """Committee-wide wire flow rollup (ISSUE 19), or None when no
        node exported an ENABLED flows section.  The perfgate ``net``
        block and the scaling table read this instead of re-deriving it
        from raw snapshots."""
        live = [f for f in self.flow_docs if f.get("enabled")]
        if not live:
            return None
        tx = sum(f.get("tx_bytes", 0) for f in live)
        rx = sum(f.get("rx_bytes", 0) for f in live)
        cls_tx: dict[str, int] = {}
        cls_fr: dict[str, int] = {}
        for f in live:
            for cls, ent in (f.get("classes") or {}).items():
                cls_tx[cls] = cls_tx.get(cls, 0) + ent.get("tx_bytes", 0)
                cls_fr[cls] = cls_fr.get(cls, 0) + ent.get("tx_frames", 0)
        amps = sorted(
            a
            for f in live
            for a in [(f.get("amp") or {}).get("propose")]
            if a
        )

        def pct(p: float) -> float:
            import math

            return amps[min(len(amps) - 1, math.ceil(p * len(amps)) - 1)]

        return {
            "nodes": len(live),
            "tx_bytes": tx,
            "rx_bytes": rx,
            "retx_bytes": sum(f.get("retx_bytes", 0) for f in live),
            "retx_frames": sum(f.get("retx_frames", 0) for f in live),
            "peers_elided": sum(f.get("peers_elided", 0) for f in live),
            "class_tx_bytes": cls_tx,
            "class_tx_frames": cls_fr,
            "leader_amp_p50": pct(0.50) if amps else None,
            "leader_amp_p99": pct(0.99) if amps else None,
            "wire_bytes_per_commit": (
                round(tx / len(self.commits)) if self.commits else None
            ),
        }

    def _net_txt(self) -> str:
        """The ``+ NET`` block (wire-level flow accounting, ISSUE 19):
        committee-wide egress/ingress, wire bytes per commit, per-class
        egress shares (they sum to 100% of accounted bytes — every
        frame lands in exactly one class), propose-amplification
        percentiles across nodes, retransmit overhead, and the
        compact-QC-on-wire vs vote-list-equivalent comparison."""
        if not self.flow_docs:
            return ""
        net = self.net_summary()
        lines = [" + NET (wire flow accounting):\n"]
        if net is None:
            lines.append(
                " Flow accounting: n/a (disabled — HOTSTUFF_NET=0)\n"
            )
            return "".join(lines)
        tx, rx = net["tx_bytes"], net["rx_bytes"]
        _, dur = self.consensus_throughput()
        rate_txt = f" ({round(tx / dur):,} B/s)" if dur and tx else ""
        lines.append(
            f" Wire egress: {tx:,} B across {net['nodes']}"
            f" node(s){rate_txt}\n"
        )
        lines.append(f" Wire ingress: {rx:,} B\n")
        wpc = net["wire_bytes_per_commit"]
        lines.append(
            f" Wire bytes per commit: {wpc:,} B egress"
            f" ({len(self.commits)} commits)\n"
            if wpc is not None
            else " Wire bytes per commit: n/a (no commits in the window)\n"
        )
        if tx:
            for cls, b in sorted(
                net["class_tx_bytes"].items(), key=lambda e: (-e[1], e[0])
            ):
                if b:
                    lines.append(
                        f" Class {cls + ':':<13} {b:>12,} B egress"
                        f" ({100.0 * b / tx:5.1f}%)\n"
                    )
        if net["leader_amp_p50"] is not None:
            lines.append(
                f" Propose amplification p50/p99:"
                f" {net['leader_amp_p50']:.2f} /"
                f" {net['leader_amp_p99']:.2f}"
                " (wire/logical egress; broadcast fan-out = n-1)\n"
            )
        if tx:
            lines.append(
                f" Retransmit overhead: {net['retx_bytes']:,} B"
                f" ({100.0 * net['retx_bytes'] / tx:.2f}% of egress,"
                f" {net['retx_frames']} frame(s))\n"
            )
        # compact-QC on-wire proof: the last emitted QC's wire size vs
        # what a quorum of individual votes costs on this run's links
        # (mean accounted vote frame x 2f+1)
        vote_b = net["class_tx_bytes"].get("vote", 0)
        vote_f = net["class_tx_frames"].get("vote", 0)
        if self.qc_wire_bytes and vote_f:
            quorum = self.num_node_logs - (self.num_node_logs - 1) // 3
            votelist = round(quorum * vote_b / vote_f)
            form = "compact" if self.compact_qcs else "vote-list"
            lines.append(
                f" QC on-wire ({form}): {self.qc_wire_bytes:,} B vs"
                f" ~{votelist:,} B as a {quorum}-vote list\n"
            )
        if net["peers_elided"]:
            lines.append(
                f" Peer gauges elided: {net['peers_elided']}"
                " (beyond top-K export; counted, never silent)\n"
            )
        return "".join(lines)

    def _telemetry_breakdown_txt(self) -> str:
        """Commit-latency breakdown from the per-node telemetry
        snapshots (only for runs with telemetry enabled): where a
        committed block's wall time went — the network/aggregation edges
        of its lifecycle, plus host-dispatch vs device verify wall and
        event-loop lag as per-commit attribution lines."""
        docs = self.telemetry_docs
        if not docs:
            return ""

        def edge_stats(edge: str):
            """Count-weighted mean and worst p99 across nodes, or None
            when no node recorded the edge."""
            entries = [
                d.get("trace", {}).get("edges", {}).get(edge, {})
                for d in docs
            ]
            entries = [e for e in entries if e.get("count")]
            total = sum(e["count"] for e in entries)
            if not total:
                return None
            mean_ms = sum(e["mean_ms"] * e["count"] for e in entries) / total
            p99_ms = max(e.get("p99_ms", 0.0) for e in entries)
            return total, mean_ms, p99_ms

        rows = []
        for edge, label in (
            ("propose_to_vote", "propose -> first-vote (net + verify)"),
            ("vote_to_qc", "first-vote -> QC (aggregation)"),
            ("qc_to_commit", "QC -> commit (2-chain)"),
            ("propose_to_commit", "propose -> commit (total)"),
        ):
            s = edge_stats(edge)
            if s is not None:
                count, mean_ms, p99_ms = s
                rows.append(
                    f" {label + ':':<40} mean {mean_ms:7.1f} ms"
                    f"  p99 {p99_ms:7.1f} ms  (n={count})\n"
                )
        if not rows:
            return ""
        commits = sum(d.get("trace", {}).get("commits", 0) for d in docs)
        attribution = []
        host_wall_ms = sum(d.get("verify_wall_ms", 0.0) for d in docs)
        if commits and host_wall_ms:
            attribution.append(
                f"host verify {host_wall_ms / commits:.2f} ms/commit"
            )
        if self.verify_ewma_ms is not None:
            attribution.append(
                f"device dispatch EWMA {self.verify_ewma_ms:.1f} ms"
            )
        lags = [
            d["loop_lag_mean_ms"] for d in docs if "loop_lag_mean_ms" in d
        ]
        if lags:
            attribution.append(f"loop lag mean {mean(lags):.2f} ms")
        txt = " + COMMIT LATENCY BREAKDOWN (telemetry):\n" + "".join(rows)
        if attribution:
            txt += " Attribution: " + ", ".join(attribution) + "\n"
        return txt
