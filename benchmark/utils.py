"""File-layout conventions and console helpers.

Parity target: reference ``benchmark/benchmark/utils.py:12-134``
(``PathMaker``, ``Print``, ``progress_bar``).
"""

from __future__ import annotations

import os

#: fixed offset from a node's consensus port to its /metrics + /delta
#: endpoint — the derivation shared by the bench drivers (which pass
#: --metrics-port) and `python -m benchmark watch` (which scrapes it
#: from nothing but the committee file)
METRICS_PORT_OFFSET = 3_000


class PathMaker:
    """Every file-name convention in one place (reference utils.py:12-73)."""

    @staticmethod
    def base_path() -> str:
        return "."

    @staticmethod
    def node_crash_path() -> str:
        return os.path.join(PathMaker.base_path(), ".crash")

    @staticmethod
    def committee_file() -> str:
        return os.path.join(PathMaker.base_path(), ".committee.json")

    @staticmethod
    def parameters_file() -> str:
        return os.path.join(PathMaker.base_path(), ".parameters.json")

    @staticmethod
    def key_file(i: int) -> str:
        return os.path.join(PathMaker.base_path(), f".node_{i}.json")

    @staticmethod
    def db_path(i: int) -> str:
        return os.path.join(PathMaker.base_path(), f".db_{i}")

    @staticmethod
    def logs_path() -> str:
        return os.path.join(PathMaker.base_path(), "logs")

    @staticmethod
    def node_log_file(i: int) -> str:
        return os.path.join(PathMaker.logs_path(), f"node-{i}.log")

    @staticmethod
    def client_log_file() -> str:
        return os.path.join(PathMaker.logs_path(), "client.log")

    @staticmethod
    def journals_path() -> str:
        """Flight-recorder journal directory for local bench runs (under
        logs/ so _cleanup_files resets it with everything else)."""
        return os.path.join(PathMaker.logs_path(), "journals")

    @staticmethod
    def trace_file() -> str:
        """The merged Chrome trace-event JSON (open in Perfetto)."""
        return os.path.join(PathMaker.logs_path(), "trace.json")

    @staticmethod
    def campaign_file() -> str:
        """The merged campaign report artifact (`benchmark traces`
        folds every node's <node>-campaign.json ring into it)."""
        return os.path.join(PathMaker.logs_path(), "campaign.json")

    @staticmethod
    def critpath_file() -> str:
        """The machine-readable commit critical-path attribution
        document (`benchmark critpath` writes it; `--diff` reads one)."""
        return os.path.join(PathMaker.logs_path(), "critpath.json")

    @staticmethod
    def fault_spec_file() -> str:
        """The chaos-plane scenario spec the committee loads via
        HOTSTUFF_FAULTS (benchmark/chaos.py writes it at config time)."""
        return os.path.join(PathMaker.base_path(), ".faults.json")

    @staticmethod
    def results_path() -> str:
        return os.path.join(PathMaker.base_path(), "results")

    @staticmethod
    def result_file(faults: int, nodes: int, rate: int, verifier: str) -> str:
        return os.path.join(
            PathMaker.results_path(),
            f"bench-{faults}-{nodes}-{rate}-{verifier}.txt",
        )

    @staticmethod
    def plot_path() -> str:
        return os.path.join(PathMaker.base_path(), "plots")


class Print:
    @staticmethod
    def heading(message: str) -> None:
        print(f"\x1b[1m{message}\x1b[0m")

    @staticmethod
    def info(message: str) -> None:
        print(message)

    @staticmethod
    def warn(message: str) -> None:
        print(f"\x1b[1;33mWARN\x1b[0m: {message}")

    @staticmethod
    def error(message: str) -> None:
        print(f"\x1b[1;31mERROR\x1b[0m: {message}")


class BenchError(Exception):
    pass


def save_result(summary: str, faults, nodes, rate, verifier, ok: bool = True) -> str:
    """Append a SUMMARY block to the results file for this config.
    Append — multiple runs of the same config aggregate (reference
    results files hold ~5 runs each, SURVEY.md §6).  Failed runs
    (``ok=False``: the parser saw no commits, LogParser.has_window) are
    NOT appended: the aggregator means every block in the file, so one
    zero block would silently drag the config's reported TPS down."""
    if not ok:
        Print.warn("run produced no measurement window — result not saved")
        return ""
    os.makedirs(PathMaker.results_path(), exist_ok=True)
    path = PathMaker.result_file(faults, nodes, rate, verifier)
    with open(path, "a") as f:
        f.write(summary)
    Print.info(f"Result appended to {path}")
    return path
