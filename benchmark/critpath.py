"""`python -m benchmark critpath` — commit critical-path attribution.

Thin CLI over the pure engine (hotstuff_tpu/telemetry/critpath.py):
merge a run's flight-recorder journals (benchmark/traces.py), attribute
every commit's latency to the registered stage taxonomy, and

- print the "+ CRITPATH" SUMMARY block (p50/p99 by stage, dominant-stage
  histogram, slowest edges, regime classification, journal coverage);
- re-export the Chrome trace with the dedicated "critical path" track
  highlighting each commit's winning chain;
- write the machine-readable attribution document (logs/critpath.json);
- with ``--diff REF.json``, gate on ATTRIBUTION SHAPE: exit nonzero when
  any stage's share of commit latency regressed beyond the tolerance
  (HOTSTUFF_CRITPATH_DIFF_PP percentage points, default 10) even if the
  scalar latency held.  REF may be a committed bench reference
  (scripts/perf/BENCH_rXX.json — its parsed doc's "critpath" block), a
  bench JSON line document, or a previously written critpath.json.
"""

from __future__ import annotations

import json
import os

from hotstuff_tpu.telemetry import critpath as engine

from .utils import PathMaker, Print


def diff_share_pp() -> float:
    """The --diff share tolerance in percentage points
    (HOTSTUFF_CRITPATH_DIFF_PP, default engine.DIFF_SHARE_PP)."""
    raw = os.environ.get("HOTSTUFF_CRITPATH_DIFF_PP", "").strip()
    try:
        return float(raw) if raw else engine.DIFF_SHARE_PP
    except ValueError:
        return engine.DIFF_SHARE_PP


def load_reference_attribution(path: str) -> dict | None:
    """Extract an attribution document from ``path``: a raw
    critpath.json ({"stages": ...}), a bench JSON doc with a "critpath"
    block, or a committed reference record ({"parsed": {...}} /
    {"tail": "..."} from scripts/perf/BENCH_rXX.json)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    if "stages" in doc:
        return doc
    if isinstance(doc.get("critpath"), dict):
        return doc["critpath"]
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and isinstance(
        parsed.get("critpath"), dict
    ):
        return parsed["critpath"]
    tail = doc.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and isinstance(
                cand.get("critpath"), dict
            ):
                return cand["critpath"]
    return None


def analyze_dir(dir_path: str):
    """(TraceSet, CritPathReport) for the journals under ``dir_path``."""
    from .traces import TraceSet

    traces = TraceSet.load(dir_path)
    return traces, engine.analyze(traces)


def run_critpath(
    dir_path: str,
    out: str | None = None,
    diff_path: str | None = None,
    json_line: bool = False,
) -> int:
    """The ``benchmark critpath`` task body; returns the exit code."""
    traces, report = analyze_dir(dir_path)
    if not traces.journals:
        Print.error(f"no journal segments found under {dir_path}")
        return 1
    print(engine.render(report))
    att = report.attribution()
    doc_path = PathMaker.critpath_file()
    parent = os.path.dirname(doc_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(doc_path, "w") as f:
        json.dump(att, f, sort_keys=True)
    Print.info(f"Attribution document written to {doc_path}")
    if out and traces.blocks:
        trace_out = traces.export_chrome_trace(out, critpath=report)
        Print.info(
            f"Chrome trace (critical-path track) written to {trace_out}"
        )
    if json_line:
        print(json.dumps({"critpath": att}))
    if diff_path is not None:
        ref = load_reference_attribution(diff_path)
        if ref is None:
            Print.warn(
                f"no reference attribution in {diff_path};"
                " diff skipped (skip-if-missing)"
            )
            return 0
        fails = engine.diff(att, ref, share_pp=diff_share_pp())
        if fails:
            Print.error(
                f"attribution regressed vs {diff_path}:"
            )
            for line in fails:
                print(f"   {line}")
            return 1
        Print.info(
            f"attribution shape holds vs {diff_path}"
            f" (tolerance {diff_share_pp():.1f}pp per stage)"
        )
    return 0


__all__ = [
    "analyze_dir",
    "diff_share_pp",
    "load_reference_attribution",
    "run_critpath",
]
