"""`python -m benchmark profile` — the verify-pipeline waterfall.

Drives QC-shaped claim waves through the SAME dispatch path production
uses (AsyncVerifyService + LazyDeviceVerifier), with the span profiler
(hotstuff_tpu/telemetry/spans.py) on, and renders where each wave's wall
time went stage by stage:

    claim arrival -> coalesce.wait -> route.decide -> stage.pack ->
    stage.slot_wait -> flatten -> prepare -> dispatch ->
    device.execute -> readback -> verdict.fanout

The SUMMARY shows per-stage p50/p99 plus each stage's share of the
externally measured end-to-end latency, and a coverage line — the sum of
leaf-stage p50s over the e2e p50.  Coverage >= ~90% means the waterfall
accounts for the 0.5 ms-device / 91 ms-rig gap (ISSUE 4 acceptance);
a low number means a stage is missing its instrumentation.

``--capture DIR`` additionally wraps the largest batch size's waves in
``jax.profiler.trace(DIR)`` so the device window can be inspected in
TensorBoard/Perfetto at XLA-op granularity.
"""

from __future__ import annotations

import os
import time

from hotstuff_tpu.telemetry import spans as _spans

#: waves driven per batch size before stats (plus WARMUP_WAVES discarded)
DEFAULT_WAVES = 20
WARMUP_WAVES = 3

#: sustained wave-train mode: waves per train / trains measured
DEFAULT_TRAIN_WAVES = 8
DEFAULT_TRAIN_REPS = 10


def _percentile(values: list[float], pct: float) -> float:
    """Nearest-rank percentile over the raw per-wave samples (no
    histogram bucketing — the waterfall's point is exactness)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    k = max(0, min(len(ordered) - 1, round(pct / 100 * (len(ordered) - 1))))
    return ordered[k]


def make_qc_claim(n: int, scheme: str = "ed25519"):
    """One "shared" claim with n committee signatures over one digest —
    the QC verify shape (bench.py's make_qc_batch, claim-shaped).
    ``scheme="bls"`` builds the same claim over BLS12-381 material
    (96-byte G2 pubkeys, 48-byte G1 signatures)."""
    from hotstuff_tpu.crypto import Digest

    shared = Digest.of(b"profile block digest")
    votes = []
    pks = []
    if scheme == "bls":
        from hotstuff_tpu.crypto.bls import keygen as bls_keygen

        for i in range(n):
            pk, sk = bls_keygen(b"profile-bls" + i.to_bytes(4, "little"))
            pks.append(pk.to_bytes())
            votes.append(
                (pk.to_bytes(), sk.sign(shared.to_bytes()).to_bytes())
            )
    else:
        from hotstuff_tpu.crypto import Signature, generate_keypair

        for i in range(n):
            pk, sk = generate_keypair(b"\x33" * 32, i)
            pks.append(pk.to_bytes())
            votes.append(
                (pk.to_bytes(), Signature.new(shared, sk).to_bytes())
            )
    return ("shared", shared.to_bytes(), tuple(votes)), pks


def make_train_claims(n: int, waves: int, scheme: str = "ed25519"):
    """``waves`` distinct-digest QC claims over ONE committee.  Distinct
    digests defeat the service's cross-wave claim dedup (every wave is
    real work); a single committee keeps the device-resident key cache
    hot across the whole train."""
    from hotstuff_tpu.crypto import Digest

    if scheme == "bls":
        from hotstuff_tpu.crypto.bls import keygen as bls_keygen

        keys = [
            bls_keygen(b"train-bls" + i.to_bytes(4, "little"))
            for i in range(n)
        ]
        pks = [pk.to_bytes() for pk, _ in keys]
        claims = []
        for w in range(waves):
            d = Digest.of(b"train wave %d" % w)
            votes = tuple(
                (pk.to_bytes(), sk.sign(d.to_bytes()).to_bytes())
                for pk, sk in keys
            )
            claims.append(("shared", d.to_bytes(), votes))
        return claims, pks

    from hotstuff_tpu.crypto import Signature, generate_keypair

    keys = [generate_keypair(b"\x44" * 32, i) for i in range(n)]
    pks = [pk.to_bytes() for pk, _ in keys]
    claims = []
    for w in range(waves):
        d = Digest.of(b"train wave %d" % w)
        votes = tuple(
            (pk.to_bytes(), Signature.new(d, sk).to_bytes())
            for pk, sk in keys
        )
        claims.append(("shared", d.to_bytes(), votes))
    return claims, pks


def waterfall(span_rows: list[tuple], e2e_ms: list[float]) -> dict:
    """Aggregate drained recorder rows ``(name, t0_ns, dur_ns, depth,
    thread)`` against the externally measured per-wave ``e2e_ms``.

    Returns {"e2e_ms": {p50, p99}, "stages": {name: {p50_ms, p99_ms,
    count, pct_of_e2e}}, "coverage_pct": float} — stages ordered and
    summed per spans.LEAF_STAGES; parent spans (e2e, dispatch.wall, ...)
    are reported but never counted toward coverage."""
    by_stage: dict[str, list[float]] = {}
    for name, _t0, dur_ns, _depth, _thread in span_rows:
        by_stage.setdefault(name, []).append(dur_ns / 1e6)
    e2e_p50 = _percentile(e2e_ms, 50)
    stages: dict[str, dict] = {}
    leaf_sum = 0.0
    for name in (*_spans.LEAF_STAGES, *_spans.PARENT_STAGES):
        durs = by_stage.pop(name, None)
        if not durs:
            continue
        p50 = _percentile(durs, 50)
        # a stage may fire more than once per wave (chunked device
        # batches, fast-path retry): charge its TOTAL per wave, not one
        # sample, or coverage undercounts exactly when it matters
        per_wave = p50 * (len(durs) / max(1, len(e2e_ms)))
        stages[name] = {
            "p50_ms": round(p50, 4),
            "p99_ms": round(_percentile(durs, 99), 4),
            "count": len(durs),
            "pct_of_e2e": round(100 * per_wave / e2e_p50, 1)
            if e2e_p50 > 0
            else 0.0,
        }
        if name in _spans.LEAF_STAGES:
            leaf_sum += per_wave
    for name, durs in sorted(by_stage.items()):  # ad-hoc span names
        stages[name] = {
            "p50_ms": round(_percentile(durs, 50), 4),
            "p99_ms": round(_percentile(durs, 99), 4),
            "count": len(durs),
            "pct_of_e2e": 0.0,
        }
    return {
        "e2e_ms": {
            "p50": round(e2e_p50, 3),
            "p99": round(_percentile(e2e_ms, 99), 3),
        },
        "waves": len(e2e_ms),
        "stages": stages,
        "coverage_pct": round(100 * leaf_sum / e2e_p50, 1)
        if e2e_p50 > 0
        else 0.0,
    }


def format_waterfall(result: dict) -> str:
    """The profile SUMMARY block (one section per QC size)."""
    lines = [
        "-" * 64,
        " PROFILE SUMMARY — verify-pipeline waterfall",
        f" Verifier: {result.get('verifier', '?')}  "
        f"route: {result.get('route', '?')}  "
        f"waves/size: {result.get('waves', '?')}",
        "-" * 64,
    ]
    for n, res in sorted(result["sizes"].items()):
        e2e = res["e2e_ms"]
        lines.append(
            f" QC size {n}: e2e p50 {e2e['p50']:.3f} ms, "
            f"p99 {e2e['p99']:.3f} ms"
        )
        lines.append(
            f"   {'stage':<15} {'p50 ms':>9} {'p99 ms':>9} "
            f"{'% e2e':>6}  waterfall"
        )
        for name in (*_spans.LEAF_STAGES, *_spans.PARENT_STAGES):
            st = res["stages"].get(name)
            if st is None:
                continue
            pct = st["pct_of_e2e"]
            bar = "#" * min(32, round(pct / 3.125)) if pct else ""
            tag = " (frame)" if name in _spans.PARENT_STAGES else ""
            lines.append(
                f"   {name:<15} {st['p50_ms']:>9.4f} {st['p99_ms']:>9.4f} "
                f"{pct:>5.1f}%  {bar}{tag}"
            )
        lines.append(
            f"   coverage: leaf-stage p50s account for "
            f"{res['coverage_pct']:.1f}% of e2e p50"
        )
        lines.append("")
    lines.append("-" * 64)
    return "\n".join(lines)


def run_profile(
    sizes=(16, 64, 256),
    waves: int = DEFAULT_WAVES,
    verifier: str = "tpu",
    route: str = "device",
    capture_dir: str | None = None,
) -> dict:
    """Drive the claim waves and return the per-size waterfall dict.

    ``route="device"`` pins warmed-up waves to the device via
    HOTSTUFF_FORCE_DEVICE_ROUTE (the waterfall should measure the
    dispatch pipeline, not the adaptive router's weather calls);
    ``route="auto"`` leaves the cost-model routing in charge.
    ``verifier="cpu"`` profiles the inline host path instead;
    ``verifier="bls"`` profiles the BLS claims path (device G1
    aggregation + host pairing equality per QC).
    """
    import asyncio

    from hotstuff_tpu import telemetry
    from hotstuff_tpu.crypto.async_service import AsyncVerifyService
    from hotstuff_tpu.crypto.service import CpuVerifier

    telemetry.enable()
    rec = _spans.enable()
    forced = verifier != "cpu" and route == "device"
    if forced:
        os.environ["HOTSTUFF_FORCE_DEVICE_ROUTE"] = "1"

    scheme = "bls" if verifier == "bls" else "ed25519"
    claims = {n: make_qc_claim(n, scheme=scheme) for n in sizes}
    out: dict = {
        "verifier": verifier,
        "route": route if verifier != "cpu" else "inline",
        "waves": waves,
        "sizes": {},
    }

    async def drive() -> None:
        if verifier == "cpu":
            svc = AsyncVerifyService(CpuVerifier())
        elif verifier == "bls":
            from hotstuff_tpu.crypto.async_service import eval_claims_sync
            from hotstuff_tpu.crypto.bls.service import BlsVerifier

            # device G1 vote-signature aggregation, host pairing — the
            # production BLS committee backend (crypto/scheme.py)
            backend = BlsVerifier(aggregator="tpu")
            backend.precompute(claims[max(sizes)][1])
            # warm every aggregation kernel shape through the claims
            # path (same cold-compile argument as the ed25519 branch)
            for n in sizes:
                assert eval_claims_sync(backend, [claims[n][0]]) == [True]
            backend.dispatch_deadline_s = 30.0
            svc = AsyncVerifyService(backend, device=True)
        else:
            from hotstuff_tpu.crypto.async_service import eval_claims_sync
            from hotstuff_tpu.node.node import LazyDeviceVerifier

            backend = LazyDeviceVerifier(verifier)
            backend.precompute(claims[max(sizes)][1])
            backend.warmup(batch=max(sizes))
            # warm EVERY padded kernel shape through the real dispatch
            # view: a cold XLA compile inside a measured wave would
            # overrun the dispatch deadline and demote the whole run to
            # the CPU fallback (observed: seconds per shape)
            for n in sizes:
                assert eval_claims_sync(backend.async_backend, [claims[n][0]]) == [True]
            # a slow simulated device (JAX_PLATFORMS=cpu) must still be
            # MEASURED, not deadline-demoted mid-profile
            backend.dispatch_deadline_s = 30.0
            svc = AsyncVerifyService(backend, device=True)
        # pre-compile every wave-bucket shape (no-op unless the backend
        # advertises wave padding): a measured wave must never pay the
        # cold XLA compile for its padded bucket
        svc.warm_buckets()
        try:
            for n in sizes:
                claim = claims[n][0]
                for _ in range(WARMUP_WAVES):
                    assert (await svc.verify_claims([claim])) == [True]
                rec.drain()  # warmup spans don't belong in the stats
                capture = (
                    capture_dir is not None
                    and verifier != "cpu"
                    and n == max(sizes)
                )
                if capture:
                    try:
                        import jax

                        jax.profiler.start_trace(capture_dir)
                    except Exception as exc:  # noqa: BLE001 — capture is
                        capture = False  # best-effort, never fatal
                        print(f"jax.profiler capture unavailable: {exc}")
                e2e: list[float] = []
                try:
                    for _ in range(waves):
                        t0 = time.perf_counter()
                        ok = await svc.verify_claims([claim])
                        e2e.append((time.perf_counter() - t0) * 1e3)
                        assert ok == [True], "profiled wave failed to verify"
                finally:
                    if capture:
                        import jax

                        jax.profiler.stop_trace()
                        print(f"jax.profiler trace written under {capture_dir}")
                out["sizes"][n] = waterfall(rec.drain(), e2e)
        finally:
            if svc.device:
                svc.close()

    try:
        asyncio.run(drive())
    finally:
        if forced:
            os.environ.pop("HOTSTUFF_FORCE_DEVICE_ROUTE", None)
    return out


def run_train(
    size: int = 256,
    train: int = DEFAULT_TRAIN_WAVES,
    reps: int = DEFAULT_TRAIN_REPS,
    depth: int | None = None,
    verifier: str = "tpu",
) -> dict:
    """Sustained wave-train mode (ISSUE 5): drive ``train``
    distinct-digest QC waves BACK TO BACK through the dispatch pipeline
    and compare the amortized per-wave latency against the sequential
    single-wave p50 — overlap efficiency is the share of the per-wave
    round trip the staging/execute overlap hides.  Runs at depth 1 (the
    old single-in-flight behavior) and at ``depth`` (default:
    HOTSTUFF_VERIFY_PIPELINE) so the comparison is self-contained."""
    import asyncio

    from hotstuff_tpu.crypto.async_service import (
        AsyncVerifyService,
        eval_claims_sync,
        pipeline_depth_from_env,
    )

    depth = depth or pipeline_depth_from_env()
    scheme = "bls" if verifier == "bls" else "ed25519"
    claims, pks = make_train_claims(size, train, scheme=scheme)
    os.environ["HOTSTUFF_FORCE_DEVICE_ROUTE"] = "1"
    out: dict = {
        "verifier": verifier,
        "qc_size": size,
        "train_waves": train,
        "reps": reps,
        "depths": {},
    }

    if verifier == "bls":
        from hotstuff_tpu.crypto.bls.service import BlsVerifier

        backend = BlsVerifier(aggregator="tpu")
        backend.precompute(pks)
        assert eval_claims_sync(backend, [claims[0]]) == [True]
        backend.dispatch_deadline_s = 30.0
    else:
        from hotstuff_tpu.node.node import LazyDeviceVerifier

        backend = LazyDeviceVerifier(verifier)
        backend.precompute(pks)
        backend.warmup(batch=size)
        assert eval_claims_sync(backend.async_backend, [claims[0]]) == [True]
        # a slow simulated device must be MEASURED, not deadline-demoted
        backend.dispatch_deadline_s = 30.0

    async def drive(d: int) -> dict:
        svc = AsyncVerifyService(backend, device=True, pipeline_depth=d)
        svc.warm_buckets()
        try:
            for _ in range(WARMUP_WAVES):
                assert (await svc.verify_claims([claims[0]])) == [True]
            # singles: sequential fully-awaited waves — zero overlap,
            # the baseline the train amortization is measured against
            singles: list[float] = []
            for claim in claims:
                t0 = time.perf_counter()
                assert (await svc.verify_claims([claim])) == [True]
                singles.append((time.perf_counter() - t0) * 1e3)
            # trains: each wave submitted as its OWN dispatch (yield
            # until the dispatcher has taken the pending submission
            # before staging the next), whole train awaited at once
            trains: list[float] = []
            for _ in range(reps):
                t0 = time.perf_counter()
                futs = []
                for claim in claims:
                    futs.append(
                        asyncio.ensure_future(svc.verify_claims([claim]))
                    )
                    await asyncio.sleep(0)
                    while svc._pending:
                        await asyncio.sleep(0)
                results = await asyncio.gather(*futs)
                trains.append((time.perf_counter() - t0) * 1e3)
                assert all(r == [True] for r in results), "train wave failed"
            single_p50 = _percentile(singles, 50)
            train_p50 = _percentile(trains, 50)
            return {
                "single_wave_p50_ms": round(single_p50, 3),
                "train_p50_ms": round(train_p50, 3),
                "amortized_wave_ms": round(train_p50 / train, 3),
                "peak_inflight": svc.peak_inflight,
                "pipeline_waits": svc.pipeline_waits,
                "train_sigs_per_s": round(
                    size * train / (train_p50 / 1e3), 1
                )
                if train_p50 > 0
                else 0.0,
            }
        finally:
            svc.close()

    try:
        for d in sorted({1, depth}):
            out["depths"][d] = asyncio.run(drive(d))
    finally:
        os.environ.pop("HOTSTUFF_FORCE_DEVICE_ROUTE", None)
    base = out["depths"].get(1)
    top = out["depths"].get(depth)
    if base and top and depth > 1 and top["amortized_wave_ms"] > 0:
        out["overlap_speedup"] = round(
            base["amortized_wave_ms"] / top["amortized_wave_ms"], 3
        )
        out["overlap_efficiency_pct"] = round(
            100.0
            * (1 - top["amortized_wave_ms"] / base["amortized_wave_ms"]),
            1,
        )
    return out


def format_train(result: dict) -> str:
    """The wave-train SUMMARY block (one row per pipeline depth)."""
    lines = [
        "-" * 64,
        " PROFILE SUMMARY — sustained verify wave-train",
        f" Verifier: {result['verifier']}  QC size {result['qc_size']}  "
        f"{result['train_waves']} waves/train x {result['reps']} trains",
        "-" * 64,
        f"   {'depth':>5} {'single p50':>12} {'train p50':>11} "
        f"{'amortized':>11} {'peak':>5} {'sigs/s':>9}",
    ]
    for d, res in sorted(result["depths"].items()):
        lines.append(
            f"   {d:>5} {res['single_wave_p50_ms']:>10.3f}ms "
            f"{res['train_p50_ms']:>9.3f}ms "
            f"{res['amortized_wave_ms']:>9.3f}ms {res['peak_inflight']:>5} "
            f"{res['train_sigs_per_s']:>9.0f}"
        )
    if "overlap_speedup" in result:
        top = max(result["depths"])
        lines.append(
            f"   overlap: depth-{top} amortized wave is "
            f"{result['overlap_speedup']:.2f}x depth-1 "
            f"({result['overlap_efficiency_pct']:.1f}% of the per-wave "
            "round trip hidden by staging/execute overlap)"
        )
    lines.append("-" * 64)
    return "\n".join(lines)


__all__ = [
    "run_profile",
    "run_train",
    "waterfall",
    "format_waterfall",
    "format_train",
    "make_qc_claim",
    "make_train_claims",
    "DEFAULT_WAVES",
    "DEFAULT_TRAIN_WAVES",
]
