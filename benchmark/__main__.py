"""Harness task entry points (the reference's fabfile, without Fabric).

    python -m benchmark local   --nodes 4 --rate 1000 --duration 20
    python -m benchmark tpu     --sizes 4,8,16 --rate 1000
    python -m benchmark aggregate
    python -m benchmark plot

``local``  — one run, SUMMARY to stdout and results/.
``tpu``    — committee-size sweep co-located on this machine with the TPU
             verifier backend (the BASELINE.json `fab tpu` task).
``aggregate`` / ``plot`` — summarize / chart the results directory.
"""

from __future__ import annotations

import argparse
import sys

from .aggregate import aggregate, print_summary
from .local import LocalBench
from .utils import PathMaker, Print, save_result as _save_result


def task_local(args) -> int:
    bench = LocalBench(
        nodes=args.nodes,
        rate=args.rate,
        duration=args.duration,
        faults=args.faults,
        timeout_delay=args.timeout_delay,
        verifier=args.verifier,
        transport=args.transport,
        scheme=args.scheme,
        in_process=args.in_process,
        tx_size=args.tx_size,
        wan=args.wan,
        payload_homes=args.payload_homes,
        no_claim_dedup=args.no_claim_dedup,
        journal=args.journal,
        profile=args.profile,
        health=args.health,
    )
    if args.wait_weather is not None:
        bench.wait_weather(threshold_ms=args.wait_weather)
    parser = bench.run()
    trace_txt = ""
    if args.journal:
        from .traces import TraceSet

        traces = TraceSet.load(PathMaker.journals_path())
        trace_txt = traces.summary()
        crit_report = None
        if traces.blocks:
            from hotstuff_tpu.telemetry import critpath as crit_engine

            crit_report = crit_engine.analyze(traces)
            if crit_report.commits:
                trace_txt += crit_engine.render(crit_report)
            out = traces.export_chrome_trace(
                PathMaker.trace_file(), critpath=crit_report
            )
            Print.info(
                f"Chrome trace written to {out} "
                "(open in https://ui.perfetto.dev)"
            )
        else:
            Print.warn("journaling was on but no journal records were found")
        if args.health:
            from .traces import merge_campaigns

            campaign = merge_campaigns(
                PathMaker.journals_path(), PathMaker.campaign_file()
            )
            if campaign is not None:
                Print.info(f"Campaign report written to {campaign}")
    label = (
        args.verifier if args.scheme == "ed25519" else f"bls-{args.verifier}"
    )
    if args.no_claim_dedup:
        label += "-nodedup"
    if args.payload_homes != 1:
        label += f"-homes{args.payload_homes}"
    if args.transport != "asyncio":
        label += f"-{args.transport}"
    if args.in_process:
        label += "-1proc"
    if args.wan:
        label += "-wan"
    summary = parser.result(
        faults=args.faults, nodes=args.nodes, verifier=label, extra=trace_txt
    )
    print(summary)
    _save_result(summary, args.faults, args.nodes, args.rate, label,
                 ok=parser.has_window())
    return 0


def task_load(args) -> int:
    """Saturation sweep through the admission-controlled ingest plane
    (benchmark/loadgen.py, docs/LOAD.md): walk the offered rate up
    until goodput plateaus, then drive 2x saturation against a small
    proposer buffer and check the backpressure invariant (sheds
    observed, zero silent drop-newest).  Prints the ``+ LOAD`` SUMMARY
    block plus one machine-readable JSON line; exit code 1 when the
    overload run recorded silent drops."""
    import json

    from .loadgen import format_load_block, run_sweep

    result = run_sweep(
        nodes=args.nodes,
        start_rate=args.start_rate,
        duration=args.duration,
        max_steps=args.max_steps,
        clients=args.clients,
        conns_per_node=args.conns,
        tx_size=args.tx_size,
        seed=args.seed,
        overload_max_pending=args.overload_max_pending,
        read_fraction=args.read_fraction,
    )
    block = (
        "\n"
        "-----------------------------------------\n"
        " SUMMARY:\n"
        "-----------------------------------------\n"
        + format_load_block(result)
        + "-----------------------------------------\n"
    )
    print(block)
    _save_result(
        block,
        0,
        args.nodes,
        result["saturation_tx_s"],
        "load",
        ok=result["goodput_tx_s"] > 0,
    )
    # last line: the machine-readable document (scripts/load_check.py)
    print(json.dumps({"load": result}, default=str))
    if result["overload"]["drop_newest"]:
        Print.error("overload run recorded SILENT proposer drops")
        return 1
    return 0


def task_chaos(args) -> int:
    """One committee run under a seeded fault scenario, with the
    committee-wide safety/liveness invariant verdict appended to the
    SUMMARY as a CHAOS block.  Exit code 1 when an invariant fails."""
    import json

    from .chaos import ChaosBench

    spec = None
    if args.spec:
        with open(args.spec) as f:
            spec = json.load(f)
    bench = ChaosBench(
        scenario=args.scenario,
        seed=args.seed,
        nodes=args.nodes,
        rate=args.rate,
        duration=args.duration,
        timeout_delay=args.timeout_delay,
        verifier=args.verifier,
        transport=args.transport,
        journal=args.journal,
        health=args.health,
        spec=spec,
    )
    parser = bench.run()
    ok, chaos_txt = bench.check_invariants()
    trace_txt = ""
    if args.journal:
        from .traces import TraceSet

        traces = TraceSet.load(PathMaker.journals_path())
        trace_txt = traces.summary()
        if traces.blocks:
            out = traces.export_chrome_trace(PathMaker.trace_file())
            Print.info(
                f"Chrome trace written to {out} "
                "(open in https://ui.perfetto.dev)"
            )
        if args.health:
            from .traces import merge_campaigns

            campaign = merge_campaigns(
                PathMaker.journals_path(), PathMaker.campaign_file()
            )
            if campaign is not None:
                Print.info(f"Campaign report written to {campaign}")
    label = f"chaos-{bench.spec.get('name', args.scenario)}"
    if args.transport != "asyncio":
        label += f"-{args.transport}"
    summary = parser.result(
        faults=0, nodes=args.nodes, verifier=label,
        extra=trace_txt + chaos_txt,
    )
    print(summary)
    _save_result(summary, 0, args.nodes, args.rate, label,
                 ok=parser.has_window())
    if not ok:
        Print.error("chaos invariants FAILED")
    return 0 if ok else 1


def _explore_guided(args, out_dir: str) -> int:
    """``explore --guided``: fitness-guided schedule search (ISSUE 18).
    Same run budget as the flat sweep (--seeds); prints a GUIDED
    SUMMARY plus a machine-readable last line for
    scripts/adapt_check.py."""
    import json
    import time

    from hotstuff_tpu.sim import explore_guided

    t0 = time.monotonic()
    result = explore_guided(
        budget=args.seeds,
        nodes=args.nodes,
        start_seed=args.start,
        duration_s=args.duration,
        out_dir=out_dir,
        do_shrink=not args.no_shrink,
        corpus_path=args.corpus,
        scenarios_dir=args.scenarios_dir,
        progress=Print.info,
    )
    dt = time.monotonic() - t0
    print(
        "\n"
        "-----------------------------------------\n"
        " GUIDED EXPLORE SUMMARY:\n"
        "-----------------------------------------\n"
        f" Budget: {result.budget} schedules "
        f"({result.generations} generations, {args.nodes} nodes)\n"
        f" Passed: {result.passed}/{result.budget}\n"
        f" Invariant-threatening: {result.threats} "
        f"(best fitness {result.best_fitness})\n"
        f" Findings: {len(result.findings)}\n"
        f" Promoted: {len(result.promoted)} corpus entries, "
        f"{len(result.scenarios)} canned scenarios\n"
        f" Wall-clock: {dt:.1f}s "
        f"({dt / max(result.budget, 1):.2f}s/schedule)\n"
        "-----------------------------------------"
    )
    for f in result.findings:
        Print.error(
            f"seed {f.seed} ({f.profile}) FAILED: "
            + "; ".join(f.failures[:3])
        )
        if f.repro_dir:
            Print.error(f"  repro bundle: {f.repro_dir}")
    for path in result.scenarios:
        Print.info(f"canned scenario: {path}")
    if result.ok:
        Print.info(
            "every discovered threat was a correctly-contained attack"
        )
    else:
        Print.error("guided search found profile-expectation failures")
    # last line: the machine-readable document (scripts/adapt_check.py)
    print(json.dumps({
        "guided": {
            "budget": result.budget,
            "generations": result.generations,
            "passed": result.passed,
            "threats": result.threats,
            "best_fitness": result.best_fitness,
            "findings": len(result.findings),
            "promoted": [
                {
                    "seed": e["seed"],
                    "profile": e["profile"],
                    "ok": e["ok"],
                    "threats": e["threats"],
                    "journal_digest": e["journal_digest"],
                }
                for e in result.promoted
            ],
            "scenarios": result.scenarios,
            "regimes": result.regimes,
        }
    }))
    return 0 if result.ok else 1


def task_explore(args) -> int:
    """Seeded schedule exploration in the deterministic simulator
    (docs/SIM.md): each seed draws a fault/crash/reconfig schedule, runs
    the whole committee in one process on a virtual-time loop, and
    judges it with the production invariant stack.  Failures get a repro
    bundle plus a greedily-shrunk minimal schedule.  Exit code 1 when
    any seed fails its profile's expectation."""
    import os
    import time

    from hotstuff_tpu.sim import explore

    out_dir = args.out or os.path.join(
        PathMaker.logs_path(), "sim-explore"
    )
    if getattr(args, "guided", False):
        return _explore_guided(args, out_dir)
    t0 = time.monotonic()
    result = explore(
        seeds=args.seeds,
        nodes=args.nodes,
        start_seed=args.start,
        duration_s=args.duration,
        out_dir=out_dir,
        do_shrink=not args.no_shrink,
        progress=Print.info,
    )
    dt = time.monotonic() - t0
    print(
        "\n"
        "-----------------------------------------\n"
        " EXPLORE SUMMARY:\n"
        "-----------------------------------------\n"
        f" Seeds: {result.seeds} (start {args.start}, {args.nodes} nodes)\n"
        f" Passed: {result.passed}/{result.seeds} "
        f"(honest={result.honest} byz={result.byz})\n"
        f" Invariant-threatening: {result.threats}\n"
        f" Findings: {len(result.findings)}\n"
        f" Wall-clock: {dt:.1f}s "
        f"({dt / max(result.seeds, 1):.2f}s/seed)\n"
        "-----------------------------------------"
    )
    for f in result.findings:
        Print.error(
            f"seed {f.seed} ({f.profile}) FAILED: "
            + "; ".join(f.failures[:3])
        )
        if f.repro_dir:
            Print.error(f"  repro bundle: {f.repro_dir}")
        if f.minimal_events is not None:
            kinds = ",".join(ev["kind"] for ev in f.minimal_events)
            Print.error(
                f"  minimal schedule: {len(f.minimal_events)} "
                f"event(s) [{kinds}] — replay with "
                f"`python -m benchmark explore --seeds 1 "
                f"--start {f.seed} --nodes {args.nodes}`"
            )
    if result.ok:
        Print.info("all schedules matched their profile expectations")
    else:
        Print.error("schedule exploration found failures")
    return 0 if result.ok else 1


def task_traces(args) -> int:
    """Merge flight-recorder journals into the cross-node SUMMARY block
    and a Chrome trace-event JSON (open in https://ui.perfetto.dev)."""
    from .traces import TraceSet

    from .traces import merge_campaigns

    traces = TraceSet.load(args.dir)
    campaign = merge_campaigns(args.dir, PathMaker.campaign_file())
    if not traces.journals and campaign is None:
        Print.error(f"no journal segments found under {args.dir}")
        return 1
    if traces.journals:
        from hotstuff_tpu.telemetry import critpath as crit_engine

        report = crit_engine.analyze(traces)
        txt = traces.summary()
        if report.commits:
            txt += crit_engine.render(report)
        print(txt)
        out = traces.export_chrome_trace(args.out, critpath=report)
        Print.info(f"Chrome trace written to {out}")
    if campaign is not None:
        Print.info(f"Campaign report written to {campaign}")
    return 0


def task_critpath(args) -> int:
    """Commit critical-path attribution (telemetry/critpath.py): the
    "+ CRITPATH" SUMMARY block, the Perfetto critical-path track, the
    machine-readable attribution document, and the attribution-diff
    regression gate (``--diff``)."""
    from .critpath import run_critpath

    return run_critpath(
        args.dir,
        out=args.out,
        diff_path=args.diff,
        json_line=args.json,
    )


def task_profile(args) -> int:
    """Span-level verify-pipeline waterfall (benchmark/profile.py):
    QC-shaped claim waves through the production dispatch path with the
    profiler on, per-stage p50/p99 + %-of-e2e SUMMARY per batch size.
    ``--train N`` switches to the sustained wave-train mode instead:
    N distinct-digest waves back to back through the dispatch pipeline,
    amortized per-wave latency and overlap efficiency at depth 1 vs the
    configured pipeline depth."""
    if args.train:
        from .profile import format_train, run_train

        result = run_train(
            size=max(int(s) for s in args.sizes.split(",")),
            train=args.train,
            verifier=args.verifier,
        )
        print(format_train(result))
        return 0

    from .profile import format_waterfall, run_profile

    result = run_profile(
        sizes=tuple(int(s) for s in args.sizes.split(",")),
        waves=args.waves,
        verifier=args.verifier,
        route=args.route,
        capture_dir=args.capture,
    )
    print(format_waterfall(result))
    worst = min(
        (res["coverage_pct"] for res in result["sizes"].values()),
        default=0.0,
    )
    if worst < 90.0:
        Print.warn(
            f"waterfall coverage {worst:.1f}% < 90% — a pipeline stage "
            "is missing instrumentation for this route"
        )
    return 0


def task_tpu(args) -> int:
    """Committee sweep with the TPU crypto backend, co-located on this
    host (one TPU VM)."""
    sizes = [int(s) for s in args.sizes.split(",")]
    label = "tpu-1proc" if args.in_process else "tpu"
    for nodes in sizes:
        bench = LocalBench(
            nodes=nodes,
            rate=args.rate,
            duration=args.duration,
            faults=args.faults,
            timeout_delay=args.timeout_delay,
            verifier="tpu",
            in_process=args.in_process,
        )
        parser = bench.run()
        summary = parser.result(
            faults=args.faults, nodes=nodes, verifier=label
        )
        print(summary)
        _save_result(summary, args.faults, nodes, args.rate, label,
                     ok=parser.has_window())
    return 0


def task_remote_lifecycle(args) -> int:
    from .instance import TpuVmManager
    from .remote import RemoteBench
    from .settings import Settings

    settings = Settings.load(args.settings)
    mgr = TpuVmManager(settings)
    if args.lifecycle == "create":
        mgr.create_instances()
    elif args.lifecycle == "destroy":
        mgr.terminate_instances()
    elif args.lifecycle == "start":
        mgr.start_instances()
    elif args.lifecycle == "stop":
        mgr.stop_instances()
    elif args.lifecycle == "info":
        mgr.print_info()
    elif args.lifecycle == "install":
        RemoteBench(settings).install()
    elif args.lifecycle == "update":
        RemoteBench(settings).update()
    elif args.lifecycle == "remote-kill":
        RemoteBench(settings).kill()
    return 0


def task_remote_bench(args) -> int:
    from .remote import RemoteBench
    from .settings import Settings

    bench = RemoteBench(Settings.load(args.settings))
    bench.run(
        nodes_list=[int(s) for s in args.sizes.split(",")],
        rate_list=[int(s) for s in args.rates.split(",")],
        duration=args.duration,
        runs=args.runs,
        faults=args.faults,
        verifier=args.verifier,
        journal=args.journal,
        profile=args.profile,
        fault_plane=args.fault_plane,
        fault_seed=args.fault_seed,
        watch=args.watch,
    )
    return 0


def task_scaling(args) -> int:
    """Committee-scaling decomposition: protocol cost vs host
    starvation (benchmark/scaling.py; VERDICT r2 weak #4)."""
    from .scaling import main as scaling_main

    return scaling_main(
        sizes=[int(s) for s in args.sizes.split(",")],
        rate=args.rate,
        duration=args.duration,
        verifier=args.verifier,
    )


def task_storm(args) -> int:
    """View-change-storm micro-bench (BASELINE config 4): timeout flood,
    TC verify, and committee-scale QC verify per backend."""
    import os

    from .storm import format_report, run_storm

    results = run_storm(
        nodes=args.nodes, device=args.device, bls=not args.no_bls
    )
    report = format_report(args.nodes, results)
    print(report)
    os.makedirs(PathMaker.results_path(), exist_ok=True)
    backends = "-".join(results)
    path = os.path.join(
        PathMaker.results_path(), f"storm-{args.nodes}-{backends}.txt"
    )
    with open(path, "a") as f:
        f.write(report + "\n")
    Print.info(f"Result appended to {path}")
    return 0


def task_logs(args) -> int:
    """Re-parse an existing logs directory and print the SUMMARY
    (reference fabfile.py `logs` task)."""
    from .logs import LogParser

    parser = LogParser.process(args.dir)
    # faults/verifier are not recoverable from logs — print '?' rather
    # than plausible-looking defaults; node count = number of node logs
    print(parser.result(faults="?", nodes=parser.num_node_logs, verifier="?"))
    return 0


def task_watch(args) -> int:
    """Live fleet health dashboard against an already-running committee
    started with --health (docs/TELEMETRY.md)."""
    from .watch import task_watch as _watch

    _watch(args)
    return 0


def task_aggregate(_args) -> int:
    print_summary(aggregate())
    return 0


def task_plot(_args) -> int:
    from .plot import (
        plot_latency_vs_throughput,
        plot_robustness,
        plot_tps_vs_committee,
    )

    groups = aggregate()  # parse the results dir once for all plots
    # WAN-emulated series get their own figure: 300-900 ms WAN latencies
    # on the same linear axis as ~10 ms LAN points would compress the
    # LAN curves to an unreadable band and silently compare
    # incomparable network conditions
    wan_groups = {k: v for k, v in groups.items() if k[3].endswith("-wan")}
    lan_groups = {k: v for k, v in groups.items() if not k[3].endswith("-wan")}
    Print.info(f"Wrote {plot_latency_vs_throughput(lan_groups)}")
    Print.info(f"Wrote {plot_tps_vs_committee(lan_groups)}")
    Print.info(f"Wrote {plot_robustness(lan_groups)}")
    if wan_groups:
        # the reference's published WAN points overlaid (log-x; the
        # hardware gap stays visible)
        Print.info(
            f"Wrote {plot_latency_vs_throughput(wan_groups, reference_overlay=True)}"
        )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="benchmark")
    sub = parser.add_subparsers(dest="task", required=True)

    p = sub.add_parser("local")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument(
        "--tx-size",
        type=int,
        default=512,
        help="payload body bytes (0 = digest-only; 512 = reference parity)",
    )
    p.add_argument("--duration", type=float, default=20.0)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--timeout-delay", type=int, default=5_000)
    p.add_argument("--verifier", choices=["cpu", "tpu", "tpu-sharded", "mesh"], default="cpu")
    p.add_argument(
        "--payload-homes",
        type=int,
        default=1,
        help="nodes receiving each payload (client --homes): 1 = "
        "disjoint queues; more trades duplicate-proposal slack for "
        "earlier proposal (lower e2e latency at large committees)",
    )
    p.add_argument(
        "--wan",
        action="store_true",
        help="emulate the reference's 5-region WAN link delays "
        "(network/wan.py)",
    )
    p.add_argument("--transport", choices=["asyncio", "native"], default="asyncio")
    p.add_argument(
        "--scheme",
        choices=["ed25519", "bls"],
        default="ed25519",
        help="committee signature scheme (bls = aggregate QC verification)",
    )
    p.add_argument(
        "--in-process",
        action="store_true",
        help="co-locate the whole committee in one node process "
        "(run-many; removes OS scheduling noise on few-core hosts)",
    )
    p.add_argument(
        "--wait-weather",
        type=float,
        default=None,
        metavar="MS",
        help="block until the tunnel dispatch p50 drops below MS "
        "milliseconds before running (a good-weather window lets the "
        "adaptive router actually choose the device)",
    )
    p.add_argument(
        "--journal",
        action="store_true",
        help="enable the consensus flight recorder in every node and "
        "append the cross-node trace reconstruction to the SUMMARY "
        "(journals under logs/journals/, Chrome trace in logs/trace.json)",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="verify-pipeline span profiler on in every node "
        "(HOTSTUFF_PROFILE); combine with --journal to get the "
        "'verify pipeline' track in logs/trace.json",
    )
    p.add_argument(
        "--health",
        action="store_true",
        help="health plane on: every node runs the in-process anomaly "
        "monitor + campaign recorder (HOTSTUFF_HEALTH) and serves "
        "/metrics + /delta on port+3000 — attach a live dashboard with "
        "`python -m benchmark watch` (docs/TELEMETRY.md)",
    )
    p.add_argument(
        "--no-claim-dedup",
        action="store_true",
        help="give every core a PRIVATE verify service (no cross-core "
        "claim coalescing/dedup) — measures the per-node capability a "
        "one-node-per-host deployment would see, without the "
        "co-location artifact",
    )
    p.set_defaults(fn=task_local)

    p = sub.add_parser(
        "load",
        help="saturation sweep through the admission-controlled ingest "
        "plane: open-loop Poisson client fleet, credit-honoring, "
        "goodput-plateau detection + 2x-overload backpressure check "
        "(docs/LOAD.md)",
    )
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument(
        "--start-rate",
        type=int,
        default=500,
        help="first offered rate of the sweep (doubles per step)",
    )
    p.add_argument("--duration", type=float, default=10.0,
                   help="seconds per sweep step")
    p.add_argument("--max-steps", type=int, default=6)
    p.add_argument("--clients", type=int, default=64,
                   help="virtual clients modeled by the fleet")
    p.add_argument("--conns", type=int, default=2,
                   help="connections per node")
    p.add_argument("--tx-size", type=int, default=512)
    p.add_argument("--seed", type=int, default=1,
                   help="Poisson arrival-process seed")
    p.add_argument(
        "--overload-max-pending",
        type=int,
        default=2_000,
        help="proposer buffer cap for the 2x-overload run (small so a "
        "short window can actually reach the shed watermark)",
    )
    p.add_argument(
        "--read-fraction",
        type=float,
        default=0.0,
        help="mixed fleet: probability each arrival is a QC-anchored "
        "ledger read against the replicated execution layer instead "
        "of a write (docs/STATE.md)",
    )
    p.set_defaults(fn=task_load)

    p = sub.add_parser(
        "chaos",
        help="run a committee under a seeded fault scenario and check "
        "the safety/liveness invariants (docs/FAULTS.md)",
    )
    p.add_argument(
        "--scenario",
        default="split-brain",
        help="canned scenario name (hotstuff_tpu/faults/scenarios.py): "
        "split-brain, leader-isolation, flapping-link, "
        "rolling-crash-restart, byz-equivocate, byz-forge-qc, "
        "byz-withhold, byz-collude",
    )
    p.add_argument(
        "--spec",
        default=None,
        help="path to a custom fault-plane spec JSON (overrides "
        "--scenario/--seed)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument(
        "--duration",
        type=float,
        default=30.0,
        help="minimum window; extended automatically to cover the "
        "scenario's last heal plus the liveness bound",
    )
    p.add_argument(
        "--timeout-delay",
        type=int,
        default=1_000,
        help="consensus timeout (ms) — chaos runs default lower than "
        "`local` so view changes during outages resolve quickly",
    )
    p.add_argument("--verifier", choices=["cpu", "tpu", "tpu-sharded", "mesh"], default="cpu")
    p.add_argument("--transport", choices=["asyncio", "native"], default="asyncio")
    p.add_argument(
        "--journal",
        action="store_true",
        help="flight recorder on: fault windows appear as spans on the "
        "chaos-plane track of logs/trace.json",
    )
    p.add_argument(
        "--health",
        action="store_true",
        help="health plane on in every node (see `local --health`); "
        "detector firings land in the + HEALTH SUMMARY block and, with "
        "--journal, on the incidents track of logs/trace.json",
    )
    p.set_defaults(fn=task_chaos)

    p = sub.add_parser("tpu")
    p.add_argument("--sizes", default="4,8,16")
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument("--duration", type=float, default=20.0)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument("--timeout-delay", type=int, default=5_000)
    p.add_argument(
        "--in-process",
        action="store_true",
        help="co-locate each committee in one process (see `local`)",
    )
    p.set_defaults(fn=task_tpu)

    p = sub.add_parser(
        "profile",
        help="verify-pipeline span waterfall: where a QC verify wave's "
        "wall time goes, stage by stage (docs/TELEMETRY.md)",
    )
    p.add_argument("--sizes", default="16,64,256", help="QC sizes to profile")
    p.add_argument("--waves", type=int, default=20)
    p.add_argument(
        "--verifier",
        choices=["cpu", "tpu", "tpu-sharded", "mesh", "bls"],
        default="tpu",
        help="bls = the BLS claims path (device G1 aggregation + host "
        "pairing equality per QC)",
    )
    p.add_argument(
        "--train",
        type=int,
        default=0,
        metavar="N",
        help="sustained wave-train mode: N distinct-digest waves back "
        "to back through the dispatch pipeline (largest --sizes entry), "
        "amortized per-wave latency + overlap efficiency at depth 1 vs "
        "HOTSTUFF_VERIFY_PIPELINE",
    )
    p.add_argument(
        "--route",
        choices=["device", "auto"],
        default="device",
        help="device = pin warmed-up waves to the device "
        "(HOTSTUFF_FORCE_DEVICE_ROUTE); auto = adaptive cost-model "
        "routing as in production",
    )
    p.add_argument(
        "--capture",
        default=None,
        metavar="DIR",
        help="wrap the largest size's waves in jax.profiler.trace(DIR) "
        "for XLA-op-level inspection",
    )
    p.set_defaults(fn=task_profile)

    p = sub.add_parser("scaling")
    p.add_argument("--sizes", default="4,8,16,32")
    p.add_argument("--rate", type=int, default=1_000)
    p.add_argument("--duration", type=float, default=20.0)
    p.add_argument(
        "--verifier", choices=["cpu", "tpu", "tpu-sharded", "mesh"], default="cpu"
    )
    p.set_defaults(fn=task_scaling)

    p = sub.add_parser("storm")
    p.add_argument("--nodes", type=int, default=256)
    p.add_argument(
        "--device", action="store_true", help="also run the TPU backend"
    )
    p.add_argument("--no-bls", action="store_true")
    p.set_defaults(fn=task_storm)

    p = sub.add_parser("logs")
    p.add_argument("--dir", default=PathMaker.logs_path())
    p.set_defaults(fn=task_logs)

    p = sub.add_parser(
        "explore",
        help="seeded schedule sweep through the deterministic "
        "virtual-time simulator: whole committee in one process, "
        "invariant verdict per seed, repro bundle + shrunk minimal "
        "schedule on failure (docs/SIM.md)",
    )
    p.add_argument("--seeds", type=int, default=100,
                   help="number of consecutive seeds to run")
    p.add_argument("--start", type=int, default=0, help="first seed")
    p.add_argument("--nodes", type=int, default=4)
    p.add_argument(
        "--duration",
        type=float,
        default=None,
        help="virtual seconds per run (default: schedule-drawn; "
        "HOTSTUFF_SIM_DURATION)",
    )
    p.add_argument(
        "--out",
        default=None,
        metavar="DIR",
        help="directory for failure repro bundles "
        "(default: <logs>/sim-explore)",
    )
    p.add_argument(
        "--no-shrink",
        action="store_true",
        help="skip greedy schedule shrinking on failure",
    )
    p.add_argument(
        "--guided",
        action="store_true",
        help="fitness-guided search (adaptive adversaries + schedule "
        "mutation across generations) at the same run budget as the "
        "flat sweep; threatening schedules are shrunk and promoted",
    )
    p.add_argument(
        "--corpus",
        default=None,
        metavar="FILE",
        help="with --guided: append promoted schedules to this "
        "regression corpus (tests/data/sim_seeds.json dialect)",
    )
    p.add_argument(
        "--scenarios-dir",
        default=None,
        metavar="DIR",
        help="with --guided: emit promoted schedules as canned chaos "
        "scenario specs (consumable via `benchmark chaos --spec`)",
    )
    p.set_defaults(fn=task_explore)

    p = sub.add_parser("traces")
    p.add_argument(
        "--dir",
        default=PathMaker.journals_path(),
        help="directory holding the per-node journal segments",
    )
    p.add_argument(
        "--out",
        default=PathMaker.trace_file(),
        help="where to write the Chrome trace-event JSON",
    )
    p.set_defaults(fn=task_traces)

    p = sub.add_parser(
        "critpath",
        help="commit critical-path attribution from a run's journals: "
        "the + CRITPATH block (stage p50/p99, dominant-stage histogram, "
        "regime classification), the Perfetto critical-path track, and "
        "the attribution-diff regression gate (--diff)",
    )
    p.add_argument(
        "--dir",
        default=PathMaker.journals_path(),
        help="directory holding the per-node journal segments",
    )
    p.add_argument(
        "--out",
        default=PathMaker.trace_file(),
        help="where to write the Chrome trace-event JSON "
        "(with the critical-path track)",
    )
    p.add_argument(
        "--diff",
        default=None,
        metavar="REF.json",
        help="reference attribution to gate against (a committed "
        "scripts/perf/BENCH_rXX.json, a bench JSON doc, or a prior "
        "logs/critpath.json); exit 1 when any stage's latency share "
        "grew beyond HOTSTUFF_CRITPATH_DIFF_PP percentage points",
    )
    p.add_argument(
        "--json",
        action="store_true",
        help="also print the attribution as one machine-readable "
        "JSON line",
    )
    p.set_defaults(fn=task_critpath)

    p = sub.add_parser(
        "watch",
        help="live fleet dashboard: scrape every committee node's "
        "/delta endpoint, render per-node round/commit-rate/leader/"
        "route-mix/credit columns and run the fleet anomaly detectors "
        "(committee must be running with --health)",
    )
    p.add_argument(
        "--interval", type=float, default=1.0, help="seconds between ticks"
    )
    p.add_argument(
        "--duration",
        type=float,
        default=0.0,
        help="stop after this many seconds (0 = until interrupted)",
    )
    p.add_argument(
        "--once",
        action="store_true",
        help="render a single frame and exit (no screen clearing)",
    )
    p.add_argument(
        "--timeout-delay",
        type=int,
        default=5_000,
        help="the committee's consensus timeout (ms) — scales the "
        "leader-stall detector's k*timeout threshold",
    )
    p.set_defaults(fn=task_watch)

    p = sub.add_parser("aggregate")
    p.set_defaults(fn=task_aggregate)

    p = sub.add_parser("plot")
    p.set_defaults(fn=task_plot)

    # remote/cluster tasks (reference fabfile.py create/destroy/install/
    # start/stop/info/remote, re-targeted at TPU VMs — benchmark/remote.py)
    for name in ("create", "destroy", "start", "stop", "info", "install",
                 "update", "remote-kill"):
        p = sub.add_parser(name)
        p.add_argument("--settings", default="settings.json")
        p.set_defaults(fn=task_remote_lifecycle, lifecycle=name)

    p = sub.add_parser("remote")
    p.add_argument("--settings", default="settings.json")
    p.add_argument("--sizes", default="4,8")
    p.add_argument("--rates", default="1000")
    p.add_argument("--duration", type=float, default=30.0)
    p.add_argument("--runs", type=int, default=1)
    p.add_argument("--faults", type=int, default=0)
    p.add_argument(
        "--verifier",
        choices=["cpu", "tpu", "tpu-sharded", "mesh"],
        default="tpu",
    )
    p.add_argument(
        "--journal",
        action="store_true",
        help="flight recorder on in every remote node; journal dirs are "
        "pulled per host and merged before the cross-node trace",
    )
    p.add_argument(
        "--profile",
        action="store_true",
        help="verify-pipeline span profiler on in every remote node "
        "(spans land in the pulled journals when --journal is also set)",
    )
    p.add_argument(
        "--fault-plane",
        default=None,
        metavar="SCENARIO_OR_SPEC",
        help="run the sweep under a fault/adversary scenario: a canned "
        "name (split-brain, byz-equivocate, byz-collude, ...) or a spec "
        "JSON path; uploaded with the configs and threaded to every "
        "node via --fault-plane/--adversary",
    )
    p.add_argument(
        "--fault-seed",
        type=int,
        default=0,
        help="seed for a canned --fault-plane scenario",
    )
    p.add_argument(
        "--watch",
        action="store_true",
        help="health plane on in every remote node and a live fleet "
        "dashboard over the instance map during each run; unreachable "
        "nodes show an explicit STALE column instead of hanging the "
        "driver",
    )
    p.set_defaults(fn=task_remote_bench)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
