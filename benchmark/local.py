"""Local benchmark: a committee of node subprocesses + a client.

Parity target: reference ``LocalBench`` (benchmark/benchmark/local.py:
12-121): kill leftovers -> keygen per node -> write committee/parameters
JSON -> launch clients and nodes detached with stderr to log files ->
sleep for the duration -> kill -> parse logs. tmux is replaced by plain
``subprocess.Popen`` (same detached-process semantics, no extra
dependency); cargo build is replaced by nothing (Python needs no build
step — the C++ store engine, when built, is picked up automatically).
"""

from __future__ import annotations

import os
import re
import shutil
import signal
import subprocess
import sys
import time

from hotstuff_tpu.consensus import Committee, Parameters
from hotstuff_tpu.node.config import Secret, write_committee, write_parameters

from .logs import LogParser
from .utils import METRICS_PORT_OFFSET, BenchError, PathMaker, Print

BASE_PORT = 26_500


class LocalBench:
    def __init__(
        self,
        nodes: int = 4,
        rate: int = 1_000,
        duration: float = 20.0,
        faults: int = 0,
        timeout_delay: int = 5_000,
        sync_retry_delay: int = 10_000,
        verifier: str = "cpu",
        transport: str = "asyncio",
        base_port: int = BASE_PORT,
        scheme: str = "ed25519",
        in_process: bool = False,
        tx_size: int = 512,
        wan: bool = False,
        payload_homes: int = 1,
        no_claim_dedup: bool = False,
        journal: bool = False,
        profile: bool = False,
        health: bool = False,
    ):
        self.nodes = nodes
        self.rate = rate
        self.tx_size = tx_size
        self.payload_homes = payload_homes
        # VERDICT r4 weak #2: per-node private verify services — no
        # cross-core claim dedup, measuring undeduped per-node capability
        self.no_claim_dedup = no_claim_dedup
        # WAN emulation: write a 5-region link-delay spec and point the
        # committee at it (hotstuff_tpu/network/wan.py)
        self.wan = wan
        if wan and transport == "native":
            # the native reactor does its own I/O and applies no link
            # delays — a '-wan'-labeled result from it would feed
            # undelayed localhost numbers into the WAN comparison plot
            raise BenchError(
                "--wan requires the asyncio transport (the native "
                "reactor applies no link delays)"
            )
        self.duration = duration
        self.faults = faults
        self.timeout_delay = timeout_delay
        self.sync_retry_delay = sync_retry_delay
        self.verifier = verifier
        self.transport = transport
        self.base_port = base_port
        self.scheme = scheme
        # journal=True: flight recorder on in every node (JSONL ring
        # segments under logs/journals/, merged by benchmark/traces.py)
        self.journal = journal
        # profile=True: verify-pipeline span profiler on in every node;
        # with journal also on, the spans land in the journals and the
        # merged trace grows a "verify pipeline" track per node process
        self.profile = profile
        # health=True: live health plane on in every node — online
        # anomaly detectors + campaign recorder, and a /metrics+/delta
        # endpoint per node at consensus port + METRICS_PORT_OFFSET so
        # `python -m benchmark watch` can attach to the running fleet
        self.health = health
        # in_process=True: the whole committee co-locates in ONE node
        # process (`run-many`, the reference's in-process testbed shape,
        # main.rs:102-148).  On a host with fewer cores than nodes the
        # per-process harness measures the OS scheduler, not the
        # protocol; this mode shares one asyncio loop instead.
        self.in_process = in_process
        self._procs: list[subprocess.Popen] = []
        # node index -> its (latest) subprocess — lets subclasses target
        # individual nodes (ChaosBench crash/restart schedules)
        self._node_procs: dict[int, subprocess.Popen] = {}
        # extra environment for every spawned process — subclass hook
        # (ChaosBench injects HOTSTUFF_FAULTS here)
        self.extra_env: dict[str, str] = {}

    # ---- setup/teardown ----------------------------------------------------

    def _cleanup_files(self) -> None:
        for i in range(self.nodes):
            shutil.rmtree(PathMaker.db_path(i), ignore_errors=True)
        shutil.rmtree(PathMaker.logs_path(), ignore_errors=True)
        os.makedirs(PathMaker.logs_path(), exist_ok=True)

    def _kill_processes(self) -> None:
        for proc in self._procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.time() + 5
        for proc in self._procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                proc.kill()
        self._procs.clear()
        self._node_procs.clear()

    def _config(self) -> None:
        keys = [Secret.new(self.scheme) for _ in range(self.nodes)]
        committee = Committee.new(
            [
                (secret.name, 1, ("127.0.0.1", self.base_port + i))
                for i, secret in enumerate(keys)
            ],
            scheme=self.scheme,
            pops={s.name: s.pop for s in keys if s.pop is not None},
        )
        write_committee(committee, PathMaker.committee_file())
        if self.wan:
            import json

            from hotstuff_tpu.network.wan import build_spec

            spec = build_spec(
                [("127.0.0.1", self.base_port + i) for i in range(self.nodes)]
            )
            with open(self._wan_spec_path(), "w") as f:
                json.dump(spec, f)
        write_parameters(
            Parameters(
                timeout_delay=self.timeout_delay,
                sync_retry_delay=self.sync_retry_delay,
            ),
            PathMaker.parameters_file(),
        )
        for i, secret in enumerate(keys):
            secret.write(PathMaker.key_file(i))

    @staticmethod
    def _wan_spec_path() -> str:
        return os.path.join(PathMaker.base_path(), ".wan.json")

    def _spawn(
        self, cmd: list[str], log_file: str, append: bool = False
    ) -> subprocess.Popen:
        # append=True: a node restarted mid-run (chaos crash/restart)
        # keeps its pre-crash log — both lifetimes feed the log parser
        # and the invariant checker
        f = open(log_file, "a" if append else "w")
        # repo root (the directory holding hotstuff_tpu/), NOT cwd — the
        # harness must work from any working directory
        import hotstuff_tpu

        root = os.path.dirname(os.path.dirname(os.path.abspath(hotstuff_tpu.__file__)))
        wan_env = (
            {"HOTSTUFF_WAN_SPEC": self._wan_spec_path()} if self.wan else {}
        )
        if self.no_claim_dedup:
            wan_env["HOTSTUFF_NO_CLAIM_DEDUP"] = "1"
        if self.journal:
            wan_env["HOTSTUFF_JOURNAL"] = "1"
            wan_env["HOTSTUFF_JOURNAL_DIR"] = os.path.abspath(
                PathMaker.journals_path()
            )
        if self.profile:
            wan_env["HOTSTUFF_PROFILE"] = "1"
        if self.health:
            wan_env["HOTSTUFF_HEALTH"] = "1"
        proc = subprocess.Popen(
            cmd,
            stdout=f,
            stderr=subprocess.STDOUT,
            env={
                **os.environ,
                **wan_env,
                **self.extra_env,
                # PREPEND the repo root — clobbering an existing
                # PYTHONPATH can drop site dirs that register jax
                # backend plugins (the tunneled-TPU rig loads its
                # backend that way)
                "PYTHONPATH": os.pathsep.join(
                    p
                    for p in (root, os.environ.get("PYTHONPATH", ""))
                    if p
                ),
                # share one persistent XLA/Mosaic compilation cache across
                # the committee AND with bench/test runs: with --verifier
                # tpu every node would otherwise pay the full first
                # compile (minutes for the Pallas kernel) per run
                "JAX_COMPILATION_CACHE_DIR": os.environ.get(
                    "JAX_COMPILATION_CACHE_DIR", hotstuff_tpu.JAX_CACHE_DIR
                ),
            },
        )
        self._procs.append(proc)
        return proc

    def _node_cmd(self, i: int) -> list[str]:
        cmd = [
            sys.executable,
            "-m",
            "hotstuff_tpu.node",
            "-vv",
            "run",
            "--keys",
            PathMaker.key_file(i),
            "--committee",
            PathMaker.committee_file(),
            "--store",
            PathMaker.db_path(i),
            "--parameters",
            PathMaker.parameters_file(),
            "--verifier",
            self.verifier,
            "--transport",
            self.transport,
        ]
        if self.health:
            # deterministic scrape address: consensus port + fixed
            # offset, the same derivation `benchmark watch` applies to
            # the committee file
            cmd += [
                "--metrics-port",
                str(self.base_port + METRICS_PORT_OFFSET + i),
            ]
        return cmd

    def _client_cmd(self, py: str) -> list[str]:
        """The client process command line — subclass hook (LoadBench
        replaces the fixed-burst client with the Poisson fleet)."""
        return [
            py,
            "-m",
            "hotstuff_tpu.node.client",
            "--committee",
            PathMaker.committee_file(),
            "--rate",
            str(self.rate),
            "--size",
            str(self.tx_size),
            "--homes",
            str(self.payload_homes),
            "--duration",
            str(self.duration),
            "--warmup",
            "2",
            "--faults",
            str(self.faults),
        ]

    def _spawn_node(self, i: int, append: bool = False) -> subprocess.Popen:
        """Boot (or, with ``append=True``, re-boot) node ``i`` as its
        own process.  The store persists across restarts, so a respawned
        node rejoins from its pre-crash chain state."""
        proc = self._spawn(
            self._node_cmd(i), PathMaker.node_log_file(i), append=append
        )
        self._node_procs[i] = proc
        return proc

    # ---- the run -----------------------------------------------------------

    def wait_weather(
        self, threshold_ms: float = 5.0, max_wait_s: float = 1_800.0
    ) -> bool:
        """Block until the tunnel dispatch p50 drops below
        ``threshold_ms`` (VERDICT r5 item 1: capture the device-routed
        live win in a good-weather window).  Probes in a subprocess
        (the harness itself must not import jax); returns False when
        the window never arrived (caller proceeds and the run records
        whatever routing the weather allowed)."""
        import hotstuff_tpu

        root = os.path.dirname(
            os.path.dirname(os.path.abspath(hotstuff_tpu.__file__))
        )
        deadline = time.time() + max_wait_s
        while True:
            try:
                proc = subprocess.run(
                    [
                        sys.executable,
                        os.path.join(root, "scripts/probe_weather.py"),
                    ],
                    capture_output=True,
                    text=True,
                    cwd=root,
                    timeout=300,
                )
                line = (proc.stdout or "").strip()
            except subprocess.TimeoutExpired:
                # a probe that cannot even finish IS degraded weather —
                # treat as a failed reading, never abort the bench
                line = ""
            Print.info(f"weather gate: {line or 'probe failed'}")
            ms = None
            m = re.search(r"p50 ([\d.]+) ms", line)
            if m:
                ms = float(m.group(1))
            if ms is not None and ms < threshold_ms:
                return True
            if time.time() >= deadline:
                Print.warn(
                    f"weather gate timed out after {max_wait_s:.0f}s "
                    f"(last p50 {ms} ms >= {threshold_ms} ms); running anyway"
                )
                return False
            time.sleep(60)

    def run(self) -> LogParser:
        Print.heading(
            f"Local bench: {self.nodes} nodes ({self.faults} faults), "
            f"{self.rate} tx/s, {self.duration:.0f}s, verifier={self.verifier}"
        )
        self._cleanup_files()
        self._config()

        py = sys.executable
        try:
            # Boot the committee (skip `faults` nodes — crash-fault
            # injection, reference local.py:75-76).
            if self.in_process:
                run_many_cmd = [
                    py,
                    "-m",
                    "hotstuff_tpu.node",
                    "-vv",
                    "run-many",
                    "--keys",
                    ",".join(
                        PathMaker.key_file(i)
                        for i in range(self.nodes - self.faults)
                    ),
                    "--committee",
                    PathMaker.committee_file(),
                    "--store-prefix",
                    os.path.join(PathMaker.base_path(), ".db_"),
                    "--parameters",
                    PathMaker.parameters_file(),
                    "--verifier",
                    self.verifier,
                    "--transport",
                    self.transport,
                ]
                if self.health:
                    # one co-located process: node 0's derived port
                    # serves the whole committee's /delta
                    run_many_cmd += [
                        "--metrics-port",
                        str(self.base_port + METRICS_PORT_OFFSET),
                    ]
                self._spawn(run_many_cmd, PathMaker.node_log_file(0))
            else:
                for i in range(self.nodes - self.faults):
                    self._spawn_node(i)

            # Launch the producer-path client (subclass hook: LoadBench
            # swaps in the credit-aware open-loop fleet, loadgen.py).
            self._spawn(self._client_cmd(py), PathMaker.client_log_file())

            # Wait for the client to actually START sending before timing
            # the measurement window: boot cost varies hugely (CPU runs
            # boot in ~a second; --verifier tpu pays a device-kernel
            # warmup of seconds-to-minutes on a cold compilation cache),
            # and a fixed sleep would kill a tpu committee mid-warmup.
            boot_deadline = time.time() + max(60.0, 4.0 * self.nodes) + (
                300.0 if self.verifier.startswith("tpu") else 0.0
            )
            started = False
            while time.time() < boot_deadline:
                try:
                    with open(PathMaker.client_log_file()) as f:
                        if "Start sending transactions" in f.read():
                            started = True
                            break
                except OSError:
                    pass
                if any(p.poll() is not None for p in self._procs):
                    break  # something died — parse what we have
                time.sleep(0.5)
            if not started:
                Print.warn("client never started sending (boot timeout)")
            self._measurement_window(started)
        except (OSError, subprocess.SubprocessError) as e:
            raise BenchError(f"Failed to run benchmark: {e}") from e
        finally:
            self._kill_processes()

        return LogParser.process(PathMaker.logs_path())

    def _measurement_window(self, started: bool) -> None:
        """Wait out the measurement window.  Subclass hook: ChaosBench
        overrides this to drive the crash/restart schedule while the
        committee runs."""
        time.sleep(self.duration + 4)  # the window + drain margin
