"""Chaos bench: a local committee under a seeded fault scenario.

``ChaosBench`` extends :class:`LocalBench` with the chaos plane wired
end-to-end:

  - at config time it builds the scenario spec (hotstuff_tpu/faults/
    scenarios.py), fills in the committee's ``nodes`` map and a shared
    ``epoch_unix``, writes it to ``.faults.json``, and injects
    ``HOTSTUFF_FAULTS`` into every node's environment so each node
    constructs the same deterministic FaultPlane;
  - during the measurement window it executes the spec's process-level
    ``crashes`` schedule (SIGKILL at ``at``, respawn at ``restart_at``;
    the respawned node appends to its log and rejoins from its
    persisted store);
  - after the run it evaluates the committee-wide safety/liveness
    invariants (benchmark/invariants.py) and renders the ``+ CHAOS``
    block for the SUMMARY.

``epoch_unix`` (scenario t=0) is set to config time plus a small boot
margin — the spec file must exist before the first node boots, so the
epoch cannot observe the actual boot.  On a CPU-verifier committee the
client starts sending well inside the margin, and every canned scenario
opens its first window several seconds after t=0, so nodes always
commit under clean conditions first.
"""

from __future__ import annotations

import json
import math
import os
import signal
import time

from hotstuff_tpu.faults.scenarios import build, last_heal
from hotstuff_tpu.node.config import Secret, read_committee

from .invariants import check_run
from .local import LocalBench
from .utils import PathMaker, Print

#: seconds between config time and scenario t=0 (covers committee +
#: client boot on a CPU-verifier committee)
BOOT_MARGIN_S = 8.0


class ChaosBench(LocalBench):
    def __init__(
        self,
        scenario: str = "split-brain",
        seed: int = 0,
        nodes: int = 4,
        rate: int = 1_000,
        duration: float = 30.0,
        timeout_delay: int = 1_000,
        verifier: str = "cpu",
        transport: str = "asyncio",
        tx_size: int = 512,
        journal: bool = False,
        health: bool = False,
        spec: dict | None = None,
    ):
        # crash-fault injection (`faults` N) is the scenario's job here;
        # in_process is out — crashes target individual node processes
        super().__init__(
            nodes=nodes,
            rate=rate,
            duration=duration,
            faults=0,
            timeout_delay=timeout_delay,
            verifier=verifier,
            transport=transport,
            tx_size=tx_size,
            journal=journal,
            health=health,
        )
        self.scenario = scenario
        self.seed = seed
        self.spec = spec if spec is not None else build(
            scenario, nodes=nodes, seed=seed
        )
        self._epoch: float | None = None
        # the run must outlive the last heal by the liveness bound, or
        # the checker would fail a perfectly healthy committee for
        # being measured too briefly
        heal = last_heal(self.spec)
        if not math.isinf(heal):
            resume = self.spec.get("liveness", {}).get("resume_within_s", 20.0)
            self.duration = max(self.duration, heal + resume + 4.0)
        # node index -> short authority id, resolved from the key files
        # at config time (feeds violation attribution in the checker)
        self._authorities: dict[int, str] = {}

    # ---- config ------------------------------------------------------------

    def _config(self) -> None:
        super()._config()
        self._epoch = time.time() + BOOT_MARGIN_S
        spec = dict(self.spec)
        spec["epoch_unix"] = self._epoch
        # Resolve node index -> listen address through the ACTUAL key +
        # committee files (not a re-derived `127.0.0.1:{base_port+i}`
        # guess): a subclass or remote driver laying the committee out
        # differently would otherwise hand every node an empty fault
        # plane while the harness believed the scenario ran.
        committee = read_committee(PathMaker.committee_file())
        nodes_map: dict[str, int] = {}
        for i in range(self.nodes):
            name = Secret.read(PathMaker.key_file(i)).name
            addr = committee.address(name)
            if addr is None:
                raise RuntimeError(
                    f"key file {i} names an authority absent from the "
                    "committee file"
                )
            nodes_map[f"{addr[0]}:{addr[1]}"] = i
            self._authorities[i] = name.encode_base64()[:8]
        spec["nodes"] = nodes_map
        path = PathMaker.fault_spec_file()
        with open(path, "w") as f:
            json.dump(spec, f, indent=2)
        self.extra_env["HOTSTUFF_FAULTS"] = os.path.abspath(path)
        if spec.get("adversary"):
            # same spec file, second plane: adversarial nodes find their
            # policy schedule under the "adversary" key
            self.extra_env["HOTSTUFF_ADVERSARY"] = os.path.abspath(path)
        Print.info(
            f"chaos: scenario {self.spec.get('name')!r} seed {self.seed}, "
            f"spec -> {path} (epoch in {BOOT_MARGIN_S:.0f}s)"
        )

    # ---- crash/restart schedule --------------------------------------------

    def _measurement_window(self, started: bool) -> None:
        assert self._epoch is not None
        deadline = time.time() + self.duration + 4
        events: list[tuple[float, str, int]] = []
        for crash in self.spec.get("crashes", ()):
            node = int(crash["node"])
            events.append((self._epoch + float(crash["at"]), "kill", node))
            restart = crash.get("restart_at")
            if restart is not None:
                events.append(
                    (self._epoch + float(restart), "restart", node)
                )
        for when, action, node in sorted(events):
            if when > deadline:
                Print.warn(
                    f"chaos: {action} of node {node} falls past the "
                    "measurement window — skipped"
                )
                continue
            delay = when - time.time()
            if delay > 0:
                time.sleep(delay)
            t_rel = time.time() - self._epoch
            if action == "kill":
                proc = self._node_procs.get(node)
                if proc is not None and proc.poll() is None:
                    proc.send_signal(signal.SIGKILL)  # a crash, not a stop
                    proc.wait()
                Print.info(f"chaos: crashed node {node} (t={t_rel:.1f}s)")
            else:
                self._spawn_node(node, append=True)
                Print.info(f"chaos: restarted node {node} (t={t_rel:.1f}s)")
        remaining = deadline - time.time()
        if remaining > 0:
            time.sleep(remaining)

    # ---- verdict -----------------------------------------------------------

    def check_invariants(self) -> tuple[bool, str]:
        """Evaluate safety/liveness over the finished run's logs.
        Returns (all_ok, rendered CHAOS block)."""
        assert self._epoch is not None, "run() must complete first"
        return check_run(
            PathMaker.logs_path(),
            self.spec,
            self._epoch,
            authorities=self._authorities or None,
        )


__all__ = ["BOOT_MARGIN_S", "ChaosBench"]
