"""Chaos bench: a local committee under a seeded fault scenario.

``ChaosBench`` extends :class:`LocalBench` with the chaos plane wired
end-to-end:

  - at config time it builds the scenario spec (hotstuff_tpu/faults/
    scenarios.py), fills in the committee's ``nodes`` map and a shared
    ``epoch_unix``, writes it to ``.faults.json``, and injects
    ``HOTSTUFF_FAULTS`` into every node's environment so each node
    constructs the same deterministic FaultPlane;
  - during the measurement window it executes the spec's process-level
    ``crashes`` schedule (SIGKILL at ``at``, respawn at ``restart_at``;
    the respawned node appends to its log and rejoins from its
    persisted store);
  - after the run it evaluates the committee-wide safety/liveness
    invariants (benchmark/invariants.py) and renders the ``+ CHAOS``
    block for the SUMMARY.

``epoch_unix`` (scenario t=0) is set to config time plus a small boot
margin — the spec file must exist before the first node boots, so the
epoch cannot observe the actual boot.  On a CPU-verifier committee the
client starts sending well inside the margin, and every canned scenario
opens its first window several seconds after t=0, so nodes always
commit under clean conditions first.
"""

from __future__ import annotations

import json
import math
import os
import shutil
import signal
import sys
import time

from hotstuff_tpu.consensus import Committee
from hotstuff_tpu.faults.scenarios import build, last_heal
from hotstuff_tpu.node.config import Secret, read_committee, write_committee

from .invariants import check_run
from .local import LocalBench
from .utils import PathMaker, Print

#: seconds between config time and scenario t=0 (covers committee +
#: client boot on a CPU-verifier committee)
BOOT_MARGIN_S = 8.0

#: seconds between a reconfig submission and the joiner's boot: the
#: joiner's state-sync bootstrap is ONE-SHOT, so the certified schedule
#: links must already be committed (and in served manifests) when it
#: collects them — by this long after submission the op's block has
#: been 2-chain committed many times over on a local committee
JOIN_DELAY_S = 4.0


class ChaosBench(LocalBench):
    def __init__(
        self,
        scenario: str = "split-brain",
        seed: int = 0,
        nodes: int = 4,
        rate: int = 1_000,
        duration: float = 30.0,
        timeout_delay: int = 1_000,
        verifier: str = "cpu",
        transport: str = "asyncio",
        tx_size: int = 512,
        journal: bool = False,
        health: bool = False,
        spec: dict | None = None,
    ):
        # crash-fault injection (`faults` N) is the scenario's job here;
        # in_process is out — crashes target individual node processes
        super().__init__(
            nodes=nodes,
            rate=rate,
            duration=duration,
            faults=0,
            timeout_delay=timeout_delay,
            verifier=verifier,
            transport=transport,
            tx_size=tx_size,
            journal=journal,
            health=health,
        )
        self.scenario = scenario
        self.seed = seed
        self.spec = spec if spec is not None else build(
            scenario, nodes=nodes, seed=seed
        )
        self._epoch: float | None = None
        # the run must outlive the last heal by the liveness bound, or
        # the checker would fail a perfectly healthy committee for
        # being measured too briefly
        heal = last_heal(self.spec)
        if not math.isinf(heal):
            resume = self.spec.get("liveness", {}).get("resume_within_s", 20.0)
            self.duration = max(self.duration, heal + resume + 4.0)
        # live-reconfiguration events: joiner node indexes (>= nodes) get
        # fresh keys at config time, and the run must outlive the full
        # handoff (submit -> commit -> activation -> joiner votes ->
        # retiree grace window)
        self._join_indexes = sorted(
            {
                int(j)
                for ev in self.spec.get("reconfig", ())
                for j in ev.get("join", ())
            }
        )
        recfg_at = [
            float(ev.get("at", 0.0)) for ev in self.spec.get("reconfig", ())
        ]
        if recfg_at:
            resume = self.spec.get("liveness", {}).get("resume_within_s", 20.0)
            self.duration = max(
                self.duration, max(recfg_at) + JOIN_DELAY_S + resume + 8.0
            )
        # node index -> short authority id, resolved from the key files
        # at config time (feeds violation attribution in the checker)
        self._authorities: dict[int, str] = {}

    # ---- config ------------------------------------------------------------

    def _cleanup_files(self) -> None:
        super()._cleanup_files()
        # joiner indexes live past self.nodes, so the base cleanup loop
        # never reaches their stores — a stale joiner db would make the
        # "fresh member state-syncs in" part of the scenario a lie
        for j in self._join_indexes:
            shutil.rmtree(PathMaker.db_path(j), ignore_errors=True)

    def _config(self) -> None:
        super()._config()
        self._epoch = time.time() + BOOT_MARGIN_S
        spec = dict(self.spec)
        spec["epoch_unix"] = self._epoch
        # Resolve node index -> listen address through the ACTUAL key +
        # committee files (not a re-derived `127.0.0.1:{base_port+i}`
        # guess): a subclass or remote driver laying the committee out
        # differently would otherwise hand every node an empty fault
        # plane while the harness believed the scenario ran.
        committee = read_committee(PathMaker.committee_file())
        nodes_map: dict[str, int] = {}
        for i in range(self.nodes):
            name = Secret.read(PathMaker.key_file(i)).name
            addr = committee.address(name)
            if addr is None:
                raise RuntimeError(
                    f"key file {i} names an authority absent from the "
                    "committee file"
                )
            nodes_map[f"{addr[0]}:{addr[1]}"] = i
            self._authorities[i] = name.encode_base64()[:8]
        # Joiners are keyed NOW (the reconfig op must name their public
        # keys) but booted only after the op commits; their addresses go
        # into the map so fault rules targeting the joiner index resolve
        # inside its fault plane too.
        for j in self._join_indexes:
            secret = Secret.new(self.scheme)
            secret.write(PathMaker.key_file(j))
            nodes_map[f"127.0.0.1:{self.base_port + j}"] = j
            self._authorities[j] = secret.name.encode_base64()[:8]
        spec["nodes"] = nodes_map
        path = PathMaker.fault_spec_file()
        with open(path, "w") as f:
            json.dump(spec, f, indent=2)
        self.extra_env["HOTSTUFF_FAULTS"] = os.path.abspath(path)
        if spec.get("adversary"):
            # same spec file, second plane: adversarial nodes find their
            # policy schedule under the "adversary" key
            self.extra_env["HOTSTUFF_ADVERSARY"] = os.path.abspath(path)
        Print.info(
            f"chaos: scenario {self.spec.get('name')!r} seed {self.seed}, "
            f"spec -> {path} (epoch in {BOOT_MARGIN_S:.0f}s)"
        )

    # ---- crash/restart + reconfiguration schedule --------------------------

    def _measurement_window(self, started: bool) -> None:
        assert self._epoch is not None
        deadline = time.time() + self.duration + 4
        # (when, seq, action, payload): seq breaks wall-clock ties so the
        # sort never has to compare payloads (reconfig payloads are dicts)
        events: list[tuple[float, int, str, object]] = []
        seq = 0
        for crash in self.spec.get("crashes", ()):
            node = int(crash["node"])
            events.append(
                (self._epoch + float(crash["at"]), seq, "kill", node)
            )
            seq += 1
            restart = crash.get("restart_at")
            if restart is not None:
                events.append(
                    (self._epoch + float(restart), seq, "restart", node)
                )
                seq += 1
        for ev in self.spec.get("reconfig", ()):
            at = float(ev.get("at", 0.0))
            events.append((self._epoch + at, seq, "reconfig", ev))
            seq += 1
            # the joiner boots only after the op has committed: its
            # state-sync bootstrap is one-shot, so the served manifests
            # must already carry the certified schedule links
            for j in ev.get("join", ()):
                events.append(
                    (self._epoch + at + JOIN_DELAY_S, seq, "join", int(j))
                )
                seq += 1
        for when, _seq, action, payload in sorted(events):
            if when > deadline:
                Print.warn(
                    f"chaos: {action} ({payload}) falls past the "
                    "measurement window — skipped"
                )
                continue
            delay = when - time.time()
            if delay > 0:
                time.sleep(delay)
            t_rel = time.time() - self._epoch
            if action == "kill":
                proc = self._node_procs.get(payload)
                if proc is not None and proc.poll() is None:
                    proc.send_signal(signal.SIGKILL)  # a crash, not a stop
                    proc.wait()
                Print.info(f"chaos: crashed node {payload} (t={t_rel:.1f}s)")
            elif action == "restart":
                self._spawn_node(payload, append=True)
                Print.info(
                    f"chaos: restarted node {payload} (t={t_rel:.1f}s)"
                )
            elif action == "reconfig":
                self._submit_reconfig(payload)
                Print.info(
                    f"chaos: submitted reconfig "
                    f"(join {list(payload.get('join', ()))}, "
                    f"retire {list(payload.get('retire', ()))}, "
                    f"t={t_rel:.1f}s)"
                )
            else:  # join
                self._spawn_joiner(payload)
                Print.info(
                    f"chaos: booted joiner node {payload} (t={t_rel:.1f}s)"
                )
        remaining = deadline - time.time()
        if remaining > 0:
            time.sleep(remaining)

    def _submit_reconfig(self, event: dict) -> None:
        """Build the next epoch's committee file (current members minus
        retirees plus the pre-keyed joiners) and submit the sponsored op
        through the ``reconfig`` CLI — the same path an operator uses."""
        retire = {int(i) for i in event.get("retire", ())}
        join = sorted({int(j) for j in event.get("join", ())})
        members = [
            i for i in sorted(set(range(self.nodes)) | set(join))
            if i not in retire
        ]
        keys = [Secret.read(PathMaker.key_file(i)) for i in members]
        new_committee = Committee.new(
            [
                (secret.name, 1, ("127.0.0.1", self.base_port + i))
                for i, secret in zip(members, keys)
            ],
            scheme=self.scheme,
            pops={s.name: s.pop for s in keys if s.pop is not None},
        )
        path = os.path.join(PathMaker.base_path(), ".committee-next.json")
        write_committee(new_committee, path)
        sponsor = int(event.get("sponsor", 0))
        cmd = [
            sys.executable,
            "-m",
            "hotstuff_tpu.node",
            "-vv",
            "reconfig",
            "--keys",
            PathMaker.key_file(sponsor),
            "--committee",
            PathMaker.committee_file(),
            "--new-committee",
            path,
            "--margin",
            str(int(event.get("margin", 8))),
        ]
        # the log name must dodge both harness globs: node-*.log feeds
        # the invariant checkers, client*.log the throughput parser
        self._spawn(
            cmd, os.path.join(PathMaker.logs_path(), "reconfig-cli.log")
        )

    def _spawn_joiner(self, j: int) -> None:
        """Boot joiner ``j`` with a fresh store.  Its key is not in the
        genesis committee file, so the node comes up in join mode:
        ``HOTSTUFF_RECONFIG_LISTEN`` supplies the listen address the
        schedule will later confirm, and the one-shot state-sync
        bootstrap pulls the certified schedule links + snapshot."""
        self.extra_env["HOTSTUFF_RECONFIG_LISTEN"] = (
            f"127.0.0.1:{self.base_port + j}"
        )
        try:
            self._spawn_node(j)
        finally:
            self.extra_env.pop("HOTSTUFF_RECONFIG_LISTEN", None)

    # ---- verdict -----------------------------------------------------------

    def check_invariants(self) -> tuple[bool, str]:
        """Evaluate safety/liveness over the finished run's logs.
        Returns (all_ok, rendered CHAOS block)."""
        assert self._epoch is not None, "run() must complete first"
        return check_run(
            PathMaker.logs_path(),
            self.spec,
            self._epoch,
            authorities=self._authorities or None,
        )


__all__ = ["BOOT_MARGIN_S", "ChaosBench"]
