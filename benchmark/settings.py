"""Remote testbed settings.

Parity target: reference ``benchmark/settings.py:8-66`` +
``settings.json`` — testbed name, SSH key, ports, repo, instance
topology.  Cloud-TPU-VM flavored instead of EC2: instances are
``gcloud compute tpus tpu-vm`` resources addressed by zone, and nodes
co-locate one committee member per TPU-VM worker.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field


class SettingsError(Exception):
    pass


@dataclass
class Settings:
    testbed: str
    key_path: str
    consensus_port: int
    repo_name: str
    repo_url: str
    branch: str
    # TPU-VM topology
    zone: str
    accelerator_type: str
    runtime_version: str
    instances: int
    ssh_command: list[str] = field(
        default_factory=lambda: ["gcloud", "compute", "tpus", "tpu-vm", "ssh"]
    )
    scp_command: list[str] = field(
        default_factory=lambda: ["gcloud", "compute", "tpus", "tpu-vm", "scp"]
    )

    @classmethod
    def load(cls, path: str = "settings.json") -> "Settings":
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SettingsError(f"cannot read settings at {path}: {e}") from e
        try:
            return cls(
                testbed=data["testbed"],
                key_path=data["key"]["path"],
                consensus_port=int(data["ports"]["consensus"]),
                repo_name=data["repo"]["name"],
                repo_url=data["repo"]["url"],
                branch=data["repo"]["branch"],
                zone=data["instances"]["zone"],
                accelerator_type=data["instances"]["accelerator_type"],
                runtime_version=data["instances"]["runtime_version"],
                instances=int(data["instances"]["count"]),
                ssh_command=data.get(
                    "ssh_command",
                    ["gcloud", "compute", "tpus", "tpu-vm", "ssh"],
                ),
                scp_command=data.get(
                    "scp_command",
                    ["gcloud", "compute", "tpus", "tpu-vm", "scp"],
                ),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise SettingsError(f"malformed settings: missing {e}") from e


DEFAULT_SETTINGS = {
    "testbed": "hotstuff-tpu",
    "key": {"name": "gcp", "path": "~/.ssh/google_compute_engine"},
    "ports": {"consensus": 8000},
    "repo": {
        "name": "hotstuff_tpu",
        "url": "https://example.com/hotstuff-tpu.git",
        "branch": "main",
    },
    "instances": {
        "zone": "us-central2-b",
        "accelerator_type": "v5litepod-8",
        "runtime_version": "tpu-ubuntu2204-base",
        "count": 4,
    },
}
