"""Benchmark harness: local committee runs, log scraping, aggregation,
plotting.

Parity map (SURVEY.md §2.6): the reference's Python/Fabric harness
(``benchmark/``) with a CORRECTED log-schema contract — the reference's
``logs.py`` regexes are stale against its own fork's log format
(SURVEY.md §2.6 caveats); here the schema is defined in one place
(``logs.py``) and matched by the framework's actual log lines. Fabric is
not available in this image, so tasks are argparse subcommands
(``python -m benchmark local ...``) instead of ``fab local``; the AWS
``remote.py``/``instance.py`` orchestration is replaced by the ``tpu``
task, which co-locates the committee on one TPU VM (the BASELINE.json
``fab tpu`` deliverable).
"""
