"""Cross-node trace reconstruction from flight-recorder journals.

The per-node journals (hotstuff_tpu/telemetry/journal.py) each record one
node's view of the consensus lifecycle with that node's own clocks.  This
module merges a run's journals into one committee-wide timeline:

1. **Load** every ``*.jsonl`` ring segment under a journal directory,
   grouping records by the node named in each segment's meta line.
2. **Estimate per-node clock offsets** from matched send/recv pairs: a
   propose journaled at the leader and its recv.propose at a replica (or
   a vote.send and its recv.vote) give a one-way wall-clock delta per
   directed node pair.  The MEDIAN delta over a run approximates
   (typical network delay + clock offset) and is robust to the
   scheduling/GC outliers that poison a single extreme sample; with both
   directions measured the symmetric estimate
   ``offset = (d_ab - d_ba) / 2`` cancels the delay (NTP's classic
   assumption: symmetric paths).  Offsets are propagated from the
   best-connected reference node by BFS; nodes with NO matched pair
   (e.g. crashed before sending) degrade gracefully to offset 0 with a
   warning — never a crash.
3. **Reconstruct** every block's cross-node timeline — propose at the
   leader, receive/vote at each replica, QC formation, commit on every
   node — using corrected wall clocks for cross-node edges and raw
   monotonic clocks for same-node edges (immune to wall steps).
4. **Report**: a SUMMARY block with per-edge committee-wide gaps and
   straggler attribution (``summary()``), and a Chrome trace-event JSON
   openable in Perfetto / chrome://tracing (``export_chrome_trace()``):
   one track per node, one duration slice per block per node, one flow
   arrow per propose->recv edge, instant markers for timeouts.

Pure stdlib; no dependency on the node runtime (reads JSONL only) —
the only package import is the constant-leaf edge/stage registry
(``hotstuff_tpu/telemetry/taxonomy.py``), so every rendered edge name
comes from the same table the ``taxonomy-registry`` lint checks record
call sites against.
"""

from __future__ import annotations

import glob
import json
import os
import re
from collections import Counter, defaultdict
from statistics import mean, median

from hotstuff_tpu.telemetry.taxonomy import (
    BYZ_PREFIX,
    CONTROL_EDGES,
    FAULT_PREFIX,
    HEALTH_PREFIX,
    INGEST_PREFIX,
    NET_PREFIX,
    RECONFIG_PREFIX,
    SPAN_ANNOTATION_STAGES,
)

#: a block counts as reconstructed when its commit can be attributed —
#: the propose anchor plus at least one receive edge were journaled
_SEG_RE = re.compile(r"^(?P<prefix>.+)-(?P<seq>\d{6})\.jsonl$")


# ---- loading ---------------------------------------------------------------


def load_journals(
    dir_path: str, stats: dict | None = None
) -> dict[str, list[dict]]:
    """node id -> that node's records, merged across ring segments and
    sorted by record sequence (falling back to monotonic time for
    journals predating the ``s`` field).  Torn lines (a crash mid-write)
    are skipped; the node id comes from each segment's meta line
    (filenames are sanitized and ambiguous).

    A crash-restarted node resumes its ring and can replay records whose
    sequence numbers were already persisted (a torn tail hides the true
    max seq) — duplicates are dropped by (node, seq), first occurrence
    wins.  When ``stats`` (a dict) is passed it is filled with the merge
    accounting: ``overlap`` (deduped records), ``loaded`` /``dropped``
    totals and per-node counts (``dropped`` comes from the ring's
    cumulative no-silent-caps counter in the meta lines)."""
    by_node: dict[str, list[dict]] = defaultdict(list)
    meta_drop: dict[str, int] = defaultdict(int)
    overlap = 0
    paths = sorted(glob.glob(os.path.join(dir_path, "*.jsonl")))
    for path in paths:
        node = None
        records = []
        drop = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn line (crash mid-write)
                if rec.get("e") == "meta":
                    node = rec.get("n", node)
                    drop = max(drop, int(rec.get("drop", 0) or 0))
                    continue
                records.append(rec)
        if node is None:
            # segment lost its meta line: fall back to the filename prefix
            m = _SEG_RE.match(os.path.basename(path))
            node = m.group("prefix") if m else os.path.basename(path)
        by_node[node].extend(records)
        meta_drop[node] = max(meta_drop[node], drop)
    for node, records in by_node.items():
        seen: set[int] = set()
        deduped = []
        for r in records:
            s = r.get("s")
            if isinstance(s, int):
                if s in seen:
                    overlap += 1
                    continue
                seen.add(s)
            deduped.append(r)
        # segment files sort chronologically, so first occurrence wins;
        # order by seq when the journal carries it (restart-safe — the
        # monotonic clock resets across boots, seqs don't)
        if len(seen) == len(deduped):
            deduped.sort(key=lambda r: r.get("s", 0))
        else:
            deduped.sort(key=lambda r: r.get("m", 0))
        by_node[node] = deduped
    if stats is not None:
        loaded = {n: len(rs) for n, rs in by_node.items()}
        stats["overlap"] = overlap
        stats["loaded"] = sum(loaded.values())
        stats["dropped"] = sum(meta_drop.values())
        stats["by_node"] = {
            n: {"loaded": loaded[n], "dropped": meta_drop.get(n, 0)}
            for n in by_node
        }
    return dict(by_node)


def load_campaigns(dir_path: str) -> dict[str, dict]:
    """node id -> that node's persisted campaign ring (the
    ``<node>-campaign.json`` files the on-node recorder writes beside
    the journal segments; never matched by the ``*.jsonl`` glob above)."""
    from hotstuff_tpu.telemetry.health import CAMPAIGN_SUFFIX, CampaignRecorder

    out: dict[str, dict] = {}
    for path in sorted(
        glob.glob(os.path.join(dir_path, f"*{CAMPAIGN_SUFFIX}"))
    ):
        try:
            doc = CampaignRecorder.load(path)
        except (OSError, ValueError):
            continue  # torn write on a crashed node — merge the rest
        node = doc.get("node") or os.path.basename(path)[
            : -len(CAMPAIGN_SUFFIX)
        ]
        out[node] = doc
    return out


def merge_campaigns(dir_path: str, out_path: str) -> str | None:
    """Fold every node's campaign ring into one report artifact at
    ``out_path`` (the ``logs/campaign.json`` the traces task writes).
    Returns the path, or None when no campaign files exist.  The merged
    document keeps per-node sample series verbatim and adds a fleet
    header (nodes, per-node sample counts, common time range) so a
    campaign can be replotted without re-running anything."""
    campaigns = load_campaigns(dir_path)
    if not campaigns:
        return None
    spans = {}
    for node, doc in campaigns.items():
        ts = [s.get("t", 0.0) for s in doc.get("samples", ())]
        spans[node] = {
            "samples": len(ts),
            "from": min(ts) if ts else None,
            "to": max(ts) if ts else None,
        }
    merged = {
        "nodes": sorted(campaigns),
        "coverage": spans,
        "campaigns": campaigns,
    }
    parent = os.path.dirname(out_path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(out_path, "w") as f:
        json.dump(merged, f, sort_keys=True)
    return out_path


# ---- clock-offset estimation ----------------------------------------------


def estimate_offsets(
    journals: dict[str, list[dict]],
    warnings: list | None = None,
) -> tuple[dict[str, int], str | None]:
    """(offsets, reference): per-node wall-clock offset in ns relative
    to the reference node (``corrected = w - offset[node]``).  Per
    directed pair the MEDIAN matched send/recv delta is used (robust to
    scheduling-spike outliers); nodes with no matched message pair to
    the connected component degrade gracefully to offset 0 (their
    cross-node edges are then only as good as NTP) with a line appended
    to ``warnings`` when a list is passed — never a crash."""
    # send-side indexes: who proposed each digest (and when), and when
    # each node sent its vote for each digest
    propose_at: dict[str, tuple[str, int]] = {}
    vote_sent: dict[tuple[str, str], int] = {}
    for node, records in journals.items():
        for r in records:
            e = r.get("e")
            d, w = r.get("d"), r.get("w")
            if d is None or w is None:
                continue
            if e == "propose" and d not in propose_at:
                propose_at[d] = (node, w)
            elif e == "vote.send":
                vote_sent.setdefault((d, node), w)

    # every observed one-way delta per directed pair (sender, receiver)
    deltas: dict[tuple[str, str], list[int]] = defaultdict(list)

    for node, records in journals.items():
        for r in records:
            e = r.get("e")
            d, w = r.get("d"), r.get("w")
            if d is None or w is None:
                continue
            if e == "recv.propose":
                src = propose_at.get(d)
                if src is not None and src[0] != node:
                    deltas[(src[0], node)].append(w - src[1])
            elif e == "recv.vote":
                peer = r.get("p", "")
                sent = vote_sent.get((d, peer))
                if sent is not None and peer != node:
                    deltas[(peer, node)].append(w - sent)

    # symmetric pairwise offsets where both directions were measured
    pair_offset: dict[tuple[str, str], float] = {}
    adjacency: dict[str, set[str]] = defaultdict(set)
    for (a, b), d_ab in deltas.items():
        d_ba = deltas.get((b, a))
        if d_ba is None or (a, b) in pair_offset:
            continue
        # clock(b) - clock(a), delay cancelled under symmetric medians
        off = (median(d_ab) - median(d_ba)) / 2.0
        pair_offset[(a, b)] = off
        pair_offset[(b, a)] = -off
        adjacency[a].add(b)
        adjacency[b].add(a)

    nodes = sorted(journals)
    if not nodes:
        return {}, None
    reference = max(nodes, key=lambda n: (len(adjacency.get(n, ())), n))
    offsets: dict[str, int] = {n: 0 for n in nodes}
    seen = {reference}
    frontier = [reference]
    while frontier:
        a = frontier.pop()
        for b in adjacency.get(a, ()):
            if b in seen:
                continue
            offsets[b] = offsets[a] + int(pair_offset[(a, b)])
            seen.add(b)
            frontier.append(b)
    if warnings is not None and len(nodes) > 1:
        for n in nodes:
            if n not in seen:
                warnings.append(
                    f"node {n}: no matched send/recv pair to reference"
                    f" {reference}; clock offset defaulted to 0"
                )
    return offsets, reference


# ---- reconstruction --------------------------------------------------------


class TraceSet:
    """A run's merged, clock-aligned committee timeline."""

    def __init__(
        self,
        journals: dict[str, list[dict]],
        merge_stats: dict | None = None,
    ):
        self.journals = journals
        self.nodes = sorted(journals)
        # merge accounting from load_journals (dedup overlap, ring-drop
        # counters) — the + CRITPATH journal-coverage line reads these
        self.merge_stats: dict = merge_stats or {}
        self.offset_warnings: list[str] = []
        self.offsets, self.reference = estimate_offsets(
            journals, self.offset_warnings
        )
        # digest -> timeline; every (m, w) pair below is (node-local
        # monotonic ns, offset-corrected wall ns)
        self.blocks: dict[str, dict] = {}
        # rounds that any node journaled a local timeout for, with the
        # corrected wall time of the first complaint
        self.timeouts: dict[int, tuple[str, int]] = {}
        # producer-channel edges (ROADMAP PR 2 follow-up): per-payload
        # wait from the leader's recv.producer to its payload.first, ms
        # on that node's monotonic clock
        self.payload_waits: list[float] = []
        # chaos-plane windows: (label, w_open_corr, w_close_corr|None),
        # taken from the node that journaled the most fault edges (every
        # node journals the same scenario schedule)
        self.fault_spans: list[tuple[str, int, int | None]] = []
        # adversary-plane windows, per attacking node (unlike fault
        # windows these are NOT committee-wide — only the Byzantine
        # nodes journal them): (node, label, w_open_corr, w_close|None)
        self.byz_spans: list[tuple[str, str, int, int | None]] = []
        # individual attack events: (w_corr, node, kind, round)
        self.byz_events: list[tuple[int, str, str, int]] = []
        # ingest-plane records (ISSUE 10): (w_corr, node, kind, value).
        # "shed" carries the shed payload count in the value, "credit"
        # the granted credit window (sampled every 64th decision).
        self.ingest_events: list[tuple[int, str, str, int]] = []
        # reconfiguration-plane records (ISSUE 14): (w_corr, node, step,
        # round) per journaled epoch-change step (submit/commit/
        # activate/retire/link)
        self.reconfig_events: list[tuple[int, str, str, int]] = []
        # network-plane flow samples (ISSUE 19): (w_corr, node,
        # direction, class, cumulative bytes).  The flow accountant
        # journals one net.tx/net.rx record per HOTSTUFF_NET_SAMPLE
        # charges; the class rides the peer field, the node's cumulative
        # direction bytes ride the "u" field.
        self.net_events: list[tuple[int, str, str, str, int]] = []
        # health-plane incident windows (ISSUE 13): (node, kind,
        # w_open_corr, w_close_corr|None).  Each node's in-process
        # monitor journals open/close per detector, phase in the peer
        # field (like adversary windows, these are per-node, not
        # committee-wide).
        self.health_spans: list[tuple[str, str, int, int | None]] = []
        # verify-pipeline profiler spans (ISSUE 4): node -> list of
        # (stage, w_end_corr, dur_ns).  A span record's timestamps mark
        # the span's END; its duration rides in the "u" field.
        self.verify_spans: dict[str, list[tuple[str, int, int]]] = {}
        # pipeline occupancy annotations (ISSUE 5): node -> list of
        # (w_corr, in-flight depth).  Value-encoded span records (the
        # "u" field carries the depth, not a duration) — kept apart so
        # the waterfall rows above never treat a depth as nanoseconds.
        self.occupancy_samples: dict[str, list[tuple[int, int]]] = {}
        self._reconstruct()

    @classmethod
    def load(cls, dir_path: str) -> "TraceSet":
        stats: dict = {}
        return cls(load_journals(dir_path, stats), merge_stats=stats)

    def journal_coverage(self) -> float:
        """Fraction of journaled records still in the ring at merge time
        (1.0 = nothing rotated away).  Attribution over a truncated ring
        is visibly partial, never silently wrong."""
        loaded = self.merge_stats.get("loaded", 0)
        dropped = self.merge_stats.get("dropped", 0)
        if not dropped:
            return 1.0
        return loaded / float(loaded + dropped)

    def _corr(self, node: str, w: int) -> int:
        return w - self.offsets.get(node, 0)

    def _block(self, digest: str, round_: int) -> dict:
        info = self.blocks.get(digest)
        if info is None:
            info = self.blocks[digest] = {
                "round": round_,
                "leader": None,
                "propose": None,  # (m, w_corr) at the leader
                "recv": {},  # node -> (m, w_corr), first arrival
                "vote_send": {},  # node -> (m, w_corr)
                "recv_vote": {},  # voter -> (recv node, m, w_corr), first
                "qc_form": None,  # (node, m, w_corr), QC assembled
                "qc": None,  # (node, m, w_corr), first high-QC adoption
                "commit": {},  # node -> (m, w_corr)
            }
        elif round_ and not info["round"]:
            info["round"] = round_
        return info

    def _reconstruct(self) -> None:
        fault_edges_best: list[tuple[int, str, str]] = []
        byz_edges: list[tuple[int, str, str, str]] = []  # (w, node, kind, label)
        health_edges: list[tuple[int, str, str, str]] = []  # (w, node, kind, phase)
        for node, records in self.journals.items():
            producer_seen: dict[str, int] = {}  # digest -> monotonic ns
            fault_edges: list[tuple[int, str, str]] = []  # (w_corr, kind, label)
            for r in records:
                e = r["e"]
                if e.startswith(BYZ_PREFIX):
                    # adversary-plane records must never reach _block
                    # (their "d" may be None)
                    w = self._corr(node, r["w"])
                    kind = e[len(BYZ_PREFIX):]
                    if kind in ("open", "close"):
                        byz_edges.append((w, node, kind, r.get("p", "")))
                    else:
                        self.byz_events.append(
                            (w, node, kind, int(r.get("r", 0)))
                        )
                    continue
                if e.startswith(HEALTH_PREFIX):
                    # health-plane records must never reach _block ("d"
                    # is None); open/close phase rides the peer field
                    health_edges.append(
                        (
                            self._corr(node, r["w"]),
                            node,
                            e[len(HEALTH_PREFIX):],
                            r.get("p", ""),
                        )
                    )
                    continue
                if e.startswith(INGEST_PREFIX):
                    # admission-plane records must never reach _block
                    # either ("d" is None); the shed count / credit
                    # window rides the "u" field
                    self.ingest_events.append(
                        (
                            self._corr(node, r["w"]),
                            node,
                            e[len(INGEST_PREFIX):],
                            int(r.get("u") or 0),
                        )
                    )
                    continue
                if e.startswith(RECONFIG_PREFIX):
                    # reconfiguration-plane records must never reach
                    # _block either ("d" is None)
                    self.reconfig_events.append(
                        (
                            self._corr(node, r["w"]),
                            node,
                            e[len(RECONFIG_PREFIX):],
                            int(r.get("r", 0) or 0),
                        )
                    )
                    continue
                if e.startswith(NET_PREFIX):
                    # network-plane samples must never reach _block
                    # either ("d" is None): class in the peer field,
                    # cumulative direction bytes in the "u" field
                    self.net_events.append(
                        (
                            self._corr(node, r["w"]),
                            node,
                            e[len(NET_PREFIX):],
                            r.get("p", ""),
                            int(r.get("u") or 0),
                        )
                    )
                    continue
                if e in CONTROL_EDGES:
                    continue
                if e == "recv.producer":
                    producer_seen.setdefault(r["d"], r["m"])
                    continue
                if e == "payload.first":
                    got = producer_seen.get(r["d"])
                    if got is not None:
                        self.payload_waits.append((r["m"] - got) / 1e6)
                    continue
                if e == "span":
                    # profiler record: stage name in "p", duration in
                    # "u"; must not reach _block (d is empty)
                    dur = r.get("u")
                    if dur is not None:
                        if r["p"] in SPAN_ANNOTATION_STAGES:
                            # value annotation: "u" is in-flight depth
                            self.occupancy_samples.setdefault(
                                node, []
                            ).append((self._corr(node, r["w"]), int(dur)))
                        else:
                            self.verify_spans.setdefault(node, []).append(
                                (r["p"], self._corr(node, r["w"]), int(dur))
                            )
                    continue
                if e in (FAULT_PREFIX + "open", FAULT_PREFIX + "close"):
                    fault_edges.append(
                        (self._corr(node, r["w"]), e[len(FAULT_PREFIX):], r["p"])
                    )
                    continue
                if e == "timeout":
                    rnd = r["r"]
                    w = self._corr(node, r["w"])
                    if rnd not in self.timeouts or w < self.timeouts[rnd][1]:
                        self.timeouts[rnd] = (node, w)
                    continue
                stamp = (r["m"], self._corr(node, r["w"]))
                info = self._block(r["d"], r["r"])
                if e == "propose":
                    if info["propose"] is None:
                        info["leader"] = node
                        info["propose"] = stamp
                elif e == "recv.propose":
                    if node not in info["recv"]:
                        info["recv"][node] = stamp
                elif e == "vote.send":
                    info["vote_send"].setdefault(node, stamp)
                elif e == "recv.vote":
                    voter = r.get("p", "")
                    if voter and voter not in info["recv_vote"]:
                        info["recv_vote"][voter] = (node, r["m"], stamp[1])
                elif e == "qc.form":
                    if info["qc_form"] is None:
                        info["qc_form"] = (node, r["m"], stamp[1])
                elif e == "qc":
                    if info["qc"] is None:
                        info["qc"] = (node, r["m"], stamp[1])
                elif e == "commit":
                    info["commit"].setdefault(node, stamp)
            if len(fault_edges) > len(fault_edges_best):
                fault_edges_best = fault_edges
        # pair open/close edges per label, in time order
        open_at: dict[str, int] = {}
        for w, kind, label in sorted(fault_edges_best):
            if kind == "open":
                open_at.setdefault(label, w)
            elif label in open_at:
                self.fault_spans.append((label, open_at.pop(label), w))
        for label, w in open_at.items():  # never-closed windows
            self.fault_spans.append((label, w, None))
        self.fault_spans.sort(key=lambda s: s[1])
        # adversary windows pair per (node, label) — each Byzantine node
        # journals only its own schedule
        byz_open: dict[tuple[str, str], int] = {}
        for w, node, kind, label in sorted(byz_edges):
            key = (node, label)
            if kind == "open":
                byz_open.setdefault(key, w)
            elif key in byz_open:
                self.byz_spans.append((node, label, byz_open.pop(key), w))
        for (node, label), w in byz_open.items():
            self.byz_spans.append((node, label, w, None))
        self.byz_spans.sort(key=lambda s: s[2])
        self.byz_events.sort()
        self.ingest_events.sort()
        self.reconfig_events.sort()
        self.net_events.sort()
        # health incidents pair per (node, detector kind) — each node's
        # monitor journals only its own firings
        health_open: dict[tuple[str, str], int] = {}
        for w, node, kind, phase in sorted(health_edges):
            key = (node, kind)
            if phase == "open":
                health_open.setdefault(key, w)
            elif key in health_open:
                self.health_spans.append((node, kind, health_open.pop(key), w))
        for (node, kind), w in health_open.items():  # still-open incidents
            self.health_spans.append((node, kind, w, None))
        self.health_spans.sort(key=lambda s: s[2])

    # ---- derived views -----------------------------------------------------

    def committed(self) -> list[str]:
        """Digests with at least one commit record, oldest round first."""
        return sorted(
            (d for d, i in self.blocks.items() if i["commit"]),
            key=lambda d: self.blocks[d]["round"],
        )

    def reconstructed(self) -> list[str]:
        """Committed digests whose commit can be ATTRIBUTED: the propose
        anchor and at least one receive edge were journaled."""
        return [
            d
            for d in self.committed()
            if self.blocks[d]["propose"] is not None
            and self.blocks[d]["recv"]
        ]

    def coverage(self) -> float:
        committed = self.committed()
        if not committed:
            return 0.0
        return len(self.reconstructed()) / len(committed)

    def edge_gaps(self) -> dict:
        """Committee-wide per-edge statistics (ms floats) over the
        reconstructed blocks.  Cross-node edges use corrected wall
        clocks; same-node edges use that node's monotonic clock."""
        pr: list[float] = []  # propose -> replica recv (cross-node)
        spread: list[float] = []  # recv spread across replicas, per block
        rv: list[float] = []  # recv -> vote sent (same node, monotonic)
        pq: list[float] = []  # propose -> QC formed (cross-node)
        pc: list[float] = []  # propose -> commit (cross-node, all nodes)
        cspread: list[float] = []  # commit spread across nodes, per block
        recv_last: Counter = Counter()  # straggler: last to receive
        commit_last: Counter = Counter()  # straggler: last to commit
        for d in self.reconstructed():
            info = self.blocks[d]
            _, w0 = info["propose"]
            recvs = info["recv"]
            ws = [w for _, w in recvs.values()]
            pr.extend((w - w0) / 1e6 for w in ws)
            if len(ws) >= 2:
                spread.append((max(ws) - min(ws)) / 1e6)
                recv_last[max(recvs, key=lambda n: recvs[n][1])] += 1
            for node, (m_v, _) in info["vote_send"].items():
                got = recvs.get(node)
                if got is not None:
                    rv.append((m_v - got[0]) / 1e6)
            if info["qc"] is not None:
                pq.append((info["qc"][2] - w0) / 1e6)
            commits = info["commit"]
            cws = [w for _, w in commits.values()]
            pc.extend((w - w0) / 1e6 for w in cws)
            if len(cws) >= 2:
                cspread.append((max(cws) - min(cws)) / 1e6)
                commit_last[max(commits, key=lambda n: commits[n][1])] += 1
        return {
            "propose_to_recv": pr,
            "recv_spread": spread,
            "recv_to_vote": rv,
            "propose_to_qc": pq,
            "propose_to_commit": pc,
            "commit_spread": cspread,
            "recv_straggler": recv_last,
            "commit_straggler": commit_last,
        }

    # ---- reporting ---------------------------------------------------------

    def summary(self) -> str:
        """The ``+ CROSS-NODE TRACE`` SUMMARY block (appended to the
        bench SUMMARY by ``python -m benchmark local --journal``)."""
        committed = self.committed()
        if not self.nodes:
            return ""
        lines = [" + CROSS-NODE TRACE (flight recorder):\n"]
        lines.append(
            f" Nodes journaled: {len(self.nodes)};"
            f" committed blocks reconstructed:"
            f" {len(self.reconstructed())}/{len(committed)}"
            f" ({100.0 * self.coverage():.0f}%)\n"
        )
        if self.reference is not None and len(self.nodes) > 1:
            offs = ", ".join(
                f"{n} {self.offsets.get(n, 0) / 1e6:+.2f}"
                for n in self.nodes
                if n != self.reference
            )
            lines.append(
                f" Clock offsets vs {self.reference} (ms): {offs}\n"
            )
        for warning in self.offset_warnings:
            lines.append(f" WARN {warning}\n")
        overlap = self.merge_stats.get("overlap", 0)
        if overlap:
            lines.append(
                f" Journal merge: {overlap} replayed record(s) deduped"
                f" (crash-restart overlap)\n"
            )
        dropped = self.merge_stats.get("dropped", 0)
        if dropped:
            lines.append(
                f" Journal ring dropped {dropped} record(s)"
                f" (coverage {100.0 * self.journal_coverage():.0f}%)\n"
            )
        gaps = self.edge_gaps()

        def row(label: str, values: list[float], extra: str = "") -> None:
            if not values:
                return
            lines.append(
                f" {label + ':':<34} mean {mean(values):7.2f} ms"
                f"  max {max(values):7.2f} ms{extra}\n"
            )

        row("producer recv -> proposed", self.payload_waits)
        row("propose -> replica recv", gaps["propose_to_recv"])
        row("recv spread across committee", gaps["recv_spread"])
        row("recv -> vote sent (local)", gaps["recv_to_vote"])
        row("propose -> QC formed", gaps["propose_to_qc"])
        row("propose -> commit (all nodes)", gaps["propose_to_commit"])
        row("commit spread across committee", gaps["commit_spread"])
        for counter, label in (
            (gaps["recv_straggler"], "last to receive"),
            (gaps["commit_straggler"], "last to commit"),
        ):
            if counter:
                node, hits = counter.most_common(1)[0]
                total = sum(counter.values())
                lines.append(
                    f" Straggler ({label}): {node}"
                    f" ({100.0 * hits / total:.0f}% of {total} blocks)\n"
                )
        if self.timeouts:
            rounds = sorted(self.timeouts)
            shown = ", ".join(str(r) for r in rounds[:8])
            if len(rounds) > 8:
                shown += ", ..."
            lines.append(
                f" Timed-out rounds journaled: {len(rounds)} ({shown})\n"
            )
        if self.fault_spans:
            labels = Counter(label for label, _, _ in self.fault_spans)
            shown = ", ".join(
                f"{label} x{n}" if n > 1 else label
                for label, n in sorted(labels.items())
            )
            lines.append(
                f" Fault windows journaled: {len(self.fault_spans)}"
                f" ({shown})\n"
            )
        if self.byz_spans or self.byz_events:
            kinds = Counter(kind for _w, _n, kind, _r in self.byz_events)
            attackers = sorted(
                {s[0] for s in self.byz_spans}
                | {e[1] for e in self.byz_events}
            )
            shown = ", ".join(
                f"{kind} x{c}" if c > 1 else kind
                for kind, c in sorted(kinds.items())
            )
            lines.append(
                f" Adversary plane journaled: {len(self.byz_spans)}"
                f" window(s) on {', '.join(attackers)}"
                + (f"; attacks: {shown}" if shown else "")
                + "\n"
            )
        if self.ingest_events:
            shed = sum(
                v for _w, _n, k, v in self.ingest_events if k == "shed"
            )
            credits = [
                v for _w, _n, k, v in self.ingest_events if k == "credit"
            ]
            nodes = sorted({n for _w, n, _k, _v in self.ingest_events})
            lines.append(
                f" Ingest plane journaled: {len(self.ingest_events)}"
                f" edge(s) on {', '.join(nodes)};"
                f" payloads shed: {shed}"
                + (
                    f"; credit window mean {mean(credits):.0f}"
                    if credits
                    else ""
                )
                + "\n"
            )
        if self.net_events:
            nodes = sorted({n for _w, n, _d, _c, _v in self.net_events})
            peak_tx = max(
                (v for _w, _n, d, _c, v in self.net_events if d == "tx"),
                default=0,
            )
            peak_rx = max(
                (v for _w, _n, d, _c, v in self.net_events if d == "rx"),
                default=0,
            )
            lines.append(
                f" Network plane journaled: {len(self.net_events)}"
                f" flow sample(s) on {', '.join(nodes)};"
                f" peak per-node cumulative egress {peak_tx:,} B,"
                f" ingress {peak_rx:,} B\n"
            )
        if self.reconfig_events:
            steps = Counter(s for _w, _n, s, _r in self.reconfig_events)
            shown = ", ".join(
                f"{step} x{c}" if c > 1 else step
                for step, c in sorted(steps.items())
            )
            nodes = sorted({n for _w, n, _s, _r in self.reconfig_events})
            lines.append(
                f" Reconfiguration plane journaled:"
                f" {len(self.reconfig_events)} edge(s) on"
                f" {', '.join(nodes)} ({shown})\n"
            )
        if self.health_spans:
            kinds = Counter(k for _n, k, _o, _c in self.health_spans)
            shown = ", ".join(
                f"{kind} x{c}" if c > 1 else kind
                for kind, c in sorted(kinds.items())
            )
            still_open = sum(
                1 for _n, _k, _o, c in self.health_spans if c is None
            )
            lines.append(
                f" Health incidents journaled: {len(self.health_spans)}"
                f" ({shown})"
                + (f"; {still_open} never closed" if still_open else "")
                + "\n"
            )
        if self.verify_spans:
            total: Counter = Counter()
            count = 0
            for rows in self.verify_spans.values():
                count += len(rows)
                for stage, _w, dur in rows:
                    total[stage] += dur
            top = ", ".join(
                f"{stage} {ns / 1e6:.1f} ms"
                for stage, ns in total.most_common(3)
            )
            lines.append(
                f" Verify-pipeline spans journaled: {count}"
                f" (busiest stages: {top})\n"
            )
        return "".join(lines)

    # ---- Perfetto export ---------------------------------------------------

    def chrome_trace(self, critpath=None) -> dict:
        """Chrome trace-event JSON (the dict; see export_chrome_trace).
        One track (pid) per node; per block one duration slice per node
        that saw it (leader: propose->commit, replica: recv->commit)
        with a flow arrow per propose->recv edge; timeouts as instant
        markers.  ``critpath`` (an optional
        ``telemetry.critpath.CritPathReport``) adds a dedicated
        "critical path" track highlighting each commit's winning
        chain."""
        pid_of = {n: i for i, n in enumerate(self.nodes)}
        events: list[dict] = []
        for node, pid in pid_of.items():
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": f"node {node}"},
                }
            )

        # everything is expressed in microseconds since the run's first
        # corrected wall timestamp
        anchors = [
            i["propose"][1] for i in self.blocks.values() if i["propose"]
        ]
        anchors.extend(w for _, w in self.timeouts.values())
        anchors.extend(w for _, w, _ in self.fault_spans)
        anchors.extend(w for _, _, w in self.fault_spans if w is not None)
        anchors.extend(w for _, _, w, _ in self.byz_spans)
        anchors.extend(w for _, _, _, w in self.byz_spans if w is not None)
        anchors.extend(w for w, _, _, _ in self.byz_events)
        anchors.extend(w for w, _, _, _ in self.ingest_events)
        anchors.extend(w for w, _, _, _ in self.reconfig_events)
        anchors.extend(w for w, _, _, _, _ in self.net_events)
        anchors.extend(w for _, _, w, _ in self.health_spans)
        anchors.extend(w for _, _, _, w in self.health_spans if w is not None)
        for rows in self.verify_spans.values():
            # a span's start = its end stamp minus its duration
            anchors.extend(w - dur for _, w, dur in rows)
        for samples in self.occupancy_samples.values():
            anchors.extend(w for w, _ in samples)
        if critpath is not None:
            for c in critpath.commits:
                anchors.extend(
                    s.w_start
                    for s in c.segments
                    if s.w_start is not None
                )
        if not anchors:
            return {"traceEvents": events, "displayTimeUnit": "ms"}
        base = min(anchors)
        horizon = max(anchors)

        def us(w_corr: int) -> float:
            return (w_corr - base) / 1e3

        for digest, info in sorted(
            self.blocks.items(), key=lambda kv: kv[1]["round"]
        ):
            if info["propose"] is None:
                continue
            rnd = info["round"]
            name = f"r{rnd} {digest[:8]}"
            args = {"round": rnd, "digest": digest}
            _, w0 = info["propose"]
            leader = info["leader"]
            ends = [w for _, w in info["commit"].values()]
            ends.append(w0)
            if info["qc"] is not None:
                ends.append(info["qc"][2])
            leader_end = info["commit"].get(leader)
            events.append(
                {
                    "name": name,
                    "cat": "block",
                    "ph": "X",
                    "pid": pid_of[leader],
                    "tid": 0,
                    "ts": us(w0),
                    "dur": max(
                        1.0,
                        us(leader_end[1] if leader_end else max(ends))
                        - us(w0),
                    ),
                    "args": {**args, "role": "leader"},
                }
            )
            for node, (_, w_recv) in info["recv"].items():
                end = info["commit"].get(node)
                vote = info["vote_send"].get(node)
                w_end = end[1] if end else (vote[1] if vote else w_recv)
                events.append(
                    {
                        "name": name,
                        "cat": "block",
                        "ph": "X",
                        "pid": pid_of[node],
                        "tid": 0,
                        "ts": us(w_recv),
                        "dur": max(1.0, us(w_end) - us(w_recv)),
                        "args": {**args, "role": "replica"},
                    }
                )
                # one flow arrow per propose->recv edge (flow ids must
                # be unique per arrow: digest alone would fan out)
                flow = {"cat": "flow", "name": f"propagate {name}"}
                events.append(
                    {
                        **flow,
                        "ph": "s",
                        "id": f"{digest}:{node}",
                        "pid": pid_of[leader],
                        "tid": 0,
                        "ts": us(w0),
                    }
                )
                events.append(
                    {
                        **flow,
                        "ph": "f",
                        "bp": "e",
                        "id": f"{digest}:{node}",
                        "pid": pid_of[node],
                        "tid": 0,
                        "ts": us(w_recv),
                    }
                )
        for rnd, (node, w) in sorted(self.timeouts.items()):
            events.append(
                {
                    "name": f"timeout r{rnd}",
                    "cat": "timeout",
                    "ph": "i",
                    "s": "p",
                    "pid": pid_of[node],
                    "tid": 0,
                    "ts": us(w),
                }
            )
        if self.fault_spans:
            # dedicated chaos track: partition/impairment windows as
            # duration slices spanning the whole committee timeline
            chaos_pid = len(self.nodes)
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": chaos_pid,
                    "tid": 0,
                    "args": {"name": "chaos plane"},
                }
            )
            for label, w_open, w_close in self.fault_spans:
                end = w_close if w_close is not None else horizon
                events.append(
                    {
                        "name": label,
                        "cat": "fault",
                        "ph": "X",
                        "pid": chaos_pid,
                        "tid": 0,
                        "ts": us(w_open),
                        "dur": max(1.0, us(end) - us(w_open)),
                        "args": {"label": label, "closed": w_close is not None},
                    }
                )
        if self.byz_spans or self.byz_events:
            # dedicated adversary track (one pid past the chaos plane):
            # policy windows as duration slices, one thread lane per
            # attacking node, individual attacks as instant markers
            byz_pid = len(self.nodes) + 1
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": byz_pid,
                    "tid": 0,
                    "args": {"name": "adversary plane"},
                }
            )
            attackers = sorted(
                {n for n, _l, _o, _c in self.byz_spans}
                | {n for _w, n, _k, _r in self.byz_events}
            )
            tid_of = {n: i for i, n in enumerate(attackers)}
            for n, tid in tid_of.items():
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": byz_pid,
                        "tid": tid,
                        "args": {"name": f"adversary {n}"},
                    }
                )
            for node, label, w_open, w_close in self.byz_spans:
                end = w_close if w_close is not None else horizon
                events.append(
                    {
                        "name": label,
                        "cat": "byz",
                        "ph": "X",
                        "pid": byz_pid,
                        "tid": tid_of[node],
                        "ts": us(w_open),
                        "dur": max(1.0, us(end) - us(w_open)),
                        "args": {
                            "label": label,
                            "node": node,
                            "closed": w_close is not None,
                        },
                    }
                )
            for w, node, kind, rnd in self.byz_events:
                events.append(
                    {
                        "name": f"byz {kind}" + (f" r{rnd}" if rnd else ""),
                        "cat": "byz",
                        "ph": "i",
                        "s": "t",
                        "pid": byz_pid,
                        "tid": tid_of[node],
                        "ts": us(w),
                        "args": {"kind": kind, "round": rnd, "node": node},
                    }
                )
        if self.ingest_events:
            # dedicated ingest-plane track (one pid past the adversary
            # plane): per-node lanes with admission sheds as instant
            # markers and the granted credit window as a counter series
            ingest_pid = len(self.nodes) + 2
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": ingest_pid,
                    "tid": 0,
                    "args": {"name": "ingest plane"},
                }
            )
            lanes = sorted({n for _w, n, _k, _v in self.ingest_events})
            tid_of = {n: i for i, n in enumerate(lanes)}
            for n, tid in tid_of.items():
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": ingest_pid,
                        "tid": tid,
                        "args": {"name": f"ingest {n}"},
                    }
                )
            for w, node, kind, value in self.ingest_events:
                if kind == "credit":
                    events.append(
                        {
                            "name": "ingest credit",
                            "cat": "ingest",
                            "ph": "C",
                            "pid": ingest_pid,
                            "tid": tid_of[node],
                            "ts": us(w),
                            "args": {"credit": value},
                        }
                    )
                else:
                    events.append(
                        {
                            "name": f"ingest {kind} x{value}",
                            "cat": "ingest",
                            "ph": "i",
                            "s": "t",
                            "pid": ingest_pid,
                            "tid": tid_of[node],
                            "ts": us(w),
                            "args": {
                                "kind": kind,
                                "count": value,
                                "node": node,
                            },
                        }
                    )
        if self.health_spans:
            # dedicated incidents track (one pid past the ingest plane):
            # per-node lanes, one duration slice per detector firing so
            # an incident reads directly against the consensus rounds and
            # fault windows it explains
            health_pid = len(self.nodes) + 3
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": health_pid,
                    "tid": 0,
                    "args": {"name": "incidents"},
                }
            )
            lanes = sorted({n for n, _k, _o, _c in self.health_spans})
            tid_of = {n: i for i, n in enumerate(lanes)}
            for n, tid in tid_of.items():
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": health_pid,
                        "tid": tid,
                        "args": {"name": f"health {n}"},
                    }
                )
            for node, kind, w_open, w_close in self.health_spans:
                end = w_close if w_close is not None else horizon
                events.append(
                    {
                        "name": kind,
                        "cat": "health",
                        "ph": "X",
                        "pid": health_pid,
                        "tid": tid_of[node],
                        "ts": us(w_open),
                        "dur": max(1.0, us(end) - us(w_open)),
                        "args": {
                            "kind": kind,
                            "node": node,
                            "closed": w_close is not None,
                        },
                    }
                )
        if self.reconfig_events:
            # dedicated reconfiguration track (one pid past the
            # incidents plane): per-node lanes with one instant marker
            # per journaled epoch-change step, so submit -> commit ->
            # activate -> retire reads directly against the rounds the
            # handoff spans
            reconfig_pid = len(self.nodes) + 4
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": reconfig_pid,
                    "tid": 0,
                    "args": {"name": "reconfiguration"},
                }
            )
            lanes = sorted({n for _w, n, _s, _r in self.reconfig_events})
            tid_of = {n: i for i, n in enumerate(lanes)}
            for n, tid in tid_of.items():
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": reconfig_pid,
                        "tid": tid,
                        "args": {"name": f"reconfig {n}"},
                    }
                )
            for w, node, step, rnd in self.reconfig_events:
                events.append(
                    {
                        "name": f"reconfig {step}"
                        + (f" r{rnd}" if rnd else ""),
                        "cat": "reconfig",
                        "ph": "i",
                        "s": "t",
                        "pid": reconfig_pid,
                        "tid": tid_of[node],
                        "ts": us(w),
                        "args": {"step": step, "round": rnd, "node": node},
                    }
                )
        if self.net_events:
            # dedicated network plane (one pid past the critical path):
            # one cumulative-bytes counter track per (node, direction) —
            # Perfetto renders the slope, i.e. per-node bandwidth — plus
            # one flow lane per message class with a marker per journaled
            # sample, so a propose burst reads directly against the
            # rounds and fault windows that caused it
            net_pid = len(self.nodes) + 6
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": net_pid,
                    "tid": 0,
                    "args": {"name": "network plane"},
                }
            )
            classes = sorted(
                {c for _w, _n, _d, c, _v in self.net_events if c}
            )
            tid_of = {c: i + 1 for i, c in enumerate(classes)}
            for c, tid in tid_of.items():
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": net_pid,
                        "tid": tid,
                        "args": {"name": f"flow {c}"},
                    }
                )
            for w, node, d, cls, v in self.net_events:
                events.append(
                    {
                        "name": f"net {d} {node}",
                        "cat": "net",
                        "ph": "C",
                        "pid": net_pid,
                        "tid": 0,
                        "ts": us(w),
                        "args": {"bytes": v},
                    }
                )
                if cls in tid_of:
                    events.append(
                        {
                            "name": f"{d} {cls}",
                            "cat": "net",
                            "ph": "i",
                            "s": "t",
                            "pid": net_pid,
                            "tid": tid_of[cls],
                            "ts": us(w),
                            "args": {
                                "node": node,
                                "dir": d,
                                "class": cls,
                                "cum_bytes": v,
                            },
                        }
                    )
        for node, rows in sorted(self.verify_spans.items()):
            # verify-pipeline profiler track (ISSUE 4): one thread lane
            # under the journaling node's process, so the dispatch
            # waterfall lines up against the same node's consensus
            # rounds on the shared timeline
            pid = pid_of.get(node)
            if pid is None:
                continue
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 1,
                    "args": {"name": "verify pipeline"},
                }
            )
            for stage, w_end, dur in rows:
                events.append(
                    {
                        "name": stage,
                        "cat": "verify",
                        "ph": "X",
                        "pid": pid,
                        "tid": 1,
                        "ts": us(w_end - dur),
                        "dur": max(0.1, dur / 1e3),
                        "args": {"stage": stage, "dur_ms": dur / 1e6},
                    }
                )
        for node, samples in sorted(self.occupancy_samples.items()):
            # dispatch-pipeline occupancy (ISSUE 5): a counter series on
            # the same node process as the verify-pipeline lane, so
            # in-flight depth reads directly against the waterfall
            pid = pid_of.get(node)
            if pid is None:
                continue
            for w, depth in samples:
                events.append(
                    {
                        "name": "verify inflight",
                        "cat": "verify",
                        "ph": "C",
                        "pid": pid,
                        "tid": 1,
                        "ts": us(w),
                        "args": {"inflight": depth},
                    }
                )
        if critpath is not None and critpath.commits:
            # dedicated critical-path track (one pid past the
            # reconfiguration plane): per commit, the winning causal
            # chain as contiguous stage slices — the one lane that says
            # where THIS block's wall-clock went
            crit_pid = len(self.nodes) + 5
            events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": crit_pid,
                    "tid": 0,
                    "args": {"name": "critical path"},
                }
            )
            # pipelined rounds overlap in time: cycle a few lanes so
            # consecutive chains don't stack into one malformed nest
            for lane in range(4):
                events.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": crit_pid,
                        "tid": lane,
                        "args": {"name": f"chain lane {lane}"},
                    }
                )
            for c in critpath.commits:
                for seg in c.segments:
                    if seg.w_start is None or seg.w_end is None:
                        continue
                    events.append(
                        {
                            "name": seg.stage,
                            "cat": "critpath",
                            "ph": "X",
                            "pid": crit_pid,
                            "tid": c.round % 4,
                            "ts": us(seg.w_start),
                            "dur": max(1.0, us(seg.w_end) - us(seg.w_start)),
                            "args": {
                                "stage": seg.stage,
                                "detail": seg.detail,
                                "round": c.round,
                                "digest": c.digest,
                                "ms": round(seg.ms, 3),
                            },
                        }
                    )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str, critpath=None) -> str:
        """Write the Chrome trace-event JSON; open in https://ui.perfetto.dev
        (or chrome://tracing).  Returns ``path``."""
        doc = self.chrome_trace(critpath=critpath)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


__all__ = [
    "load_journals",
    "load_campaigns",
    "merge_campaigns",
    "estimate_offsets",
    "TraceSet",
]
