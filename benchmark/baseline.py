"""Reference WAN baseline points for plot overlays.

The reference's published numbers (BASELINE.md; best run per results
file under reference benchmark/data/2-chain/results/) were measured on
10-50 m5d.8xlarge instances across five AWS regions — hardware this
framework's dev rig (one CPU core, one tunneled TPU chip) cannot match
in absolute throughput.  The overlay exists so the WAN-emulated runs
(--wan: the same 5-region delay topology on localhost) can be compared
against the reference's latency/fault-degradation SHAPE honestly,
with the hardware gap visible rather than hidden.
"""

# (label, consensus_tps, consensus_latency_ms) — 2-chain WAN, 0 faults
REFERENCE_WAN_POINTS = [
    ("ref 10 nodes (WAN, 10 hosts)", 99_512, 1_286),
    ("ref 20 nodes (WAN, 20 hosts)", 114_018, 2_328),
    ("ref 50 nodes (WAN, 50 hosts)", 97_861, 1_223),
]

# (faults, tps_range, latency_ms_range) at 10 nodes
REFERENCE_WAN_FAULTS = [
    (1, (63_000, 87_000), (2_600, 3_100)),
    (3, (8_500, 16_000), (5_400, 26_700)),
]
