"""Open-loop client-fleet load generator for the ingest plane.

The fixed-burst benchmark client (``hotstuff_tpu/node/client.py``) sends
a constant quantum 20 times a second and measures whatever commits; it
never observes the admission controller (docs/LOAD.md) because it speaks
producer frame v1 and discards every reply.  This module is the other
half of the ingest plane:

- ``run_load`` — an asyncio fleet modeling K virtual clients whose
  aggregate Poisson arrival process (seeded, exponential inter-arrival
  times) is multiplexed over M connections per node.  Arrival-driven,
  never ping-pong: an arrival that cannot be submitted right now (every
  connection out of credit or in a BUSY backoff window) is counted as
  client-side shed and dropped, NOT queued — queuing would turn the
  open loop into a closed one and hide saturation.
- credit honoring: payloads ride producer frame v2 batches
  (``encode_producer_batch``) and every typed ingest ACK resets the
  connection's credit window; a BUSY ACK additionally pauses the
  connection for the node's ``retry_after_ms`` hint.
- ``LoadBench`` — the LocalBench harness with the fleet as the client
  process and telemetry forced on, so the ``ingest`` section of each
  node's snapshot is scrapeable after the run.
- ``run_sweep`` — saturation-sweep mode: walk the offered rate upward
  (doubling) until goodput stops improving, then drive 2x the measured
  saturation rate against a deliberately small proposer buffer and
  check the backpressure invariant: sheds observed, zero silent
  drop-newest.

Latency attribution: every Nth payload is tagged with the same
``Sending sample payload <digest>`` contract line the fixed client
emits, so ``LogParser`` maps it to its committed block and
``end_to_end_latency_percentiles`` yields the client-observed p50/p99.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import random
import re
import sys

log = logging.getLogger("loadgen")

#: scheduling quantum of the arrival loop (arrivals are timestamped by
#: the Poisson process, the tick only batches their submission)
TICK = 0.01
#: optimistic pre-first-ACK credit per connection — mirrors the
#: admission controller's MIN_CREDIT floor
INITIAL_CREDIT = 64
#: target sample-tag rate (samples/s) for latency attribution; the
#: contract line is log-scraped, so tagging every payload at high rates
#: would make the client log the bottleneck
SAMPLE_TARGET_PER_S = 200

# Machine-readable result line the harness scrapes from the client log
# (one JSON document; written LAST so a truncated log fails loudly).
RE_LOAD_STATS = re.compile(r"Load stats: (\{.*\})")


class _LoadConn:
    """One credit-tracked framed connection to a node.

    The reply stream is PARSED (unlike the fixed client's discard-all
    sink): typed ingest ACKs reset the credit window and feed the
    accepted/shed counters; a legacy ``b"Ack"`` (v1 frames only) is
    ignored."""

    def __init__(self, address):
        self.address = address
        self.writer: asyncio.StreamWriter | None = None
        self._sink: asyncio.Task | None = None
        self.alive = False
        self.credit = INITIAL_CREDIT
        self.busy_until = 0.0
        self.accepted = 0
        self.shed = 0
        self.busy_frames = 0

    async def connect(self) -> None:
        from hotstuff_tpu.network.framing import set_nodelay

        reader, writer = await asyncio.open_connection(*self.address)
        try:
            set_nodelay(writer)
            sink = asyncio.ensure_future(self._read_acks(reader))
        except BaseException:
            writer.close()
            raise
        self.writer = writer
        self._sink = sink
        self.alive = True
        self.credit = INITIAL_CREDIT
        self.busy_until = 0.0

    def send_batch(self, frame: bytes, count: int) -> None:
        from hotstuff_tpu.network.framing import write_frame

        if not self.alive:
            return
        try:
            write_frame(self.writer, frame)
            self.credit -= count
        except (ConnectionError, OSError):
            self.mark_dead()

    async def drain(self, timeout: float = 1.0) -> None:
        if not self.alive:
            return
        try:
            await asyncio.wait_for(self.writer.drain(), timeout)
        except (ConnectionError, OSError, asyncio.TimeoutError):
            self.mark_dead()

    async def _read_acks(self, reader: asyncio.StreamReader) -> None:
        from hotstuff_tpu.consensus.errors import SerializationError
        from hotstuff_tpu.consensus.wire import decode_ingest_ack
        from hotstuff_tpu.network.framing import read_frame

        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await read_frame(reader)
                try:
                    ack = decode_ingest_ack(frame)
                except SerializationError:
                    continue
                if ack is None:
                    continue  # legacy v1 Ack
                self.accepted += ack.accepted
                self.shed += ack.shed
                # the ACK's credit is the node's CURRENT window — an
                # authoritative reset, not an increment
                self.credit = ack.credit
                if ack.busy:
                    self.busy_frames += 1
                    self.busy_until = max(
                        self.busy_until,
                        loop.time() + ack.retry_after_ms / 1e3,
                    )
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            self.mark_dead()

    def mark_dead(self) -> None:
        if self.alive:
            log.warning(
                "Node %s:%d unreachable; dropping until it returns",
                *self.address,
            )
        self.alive = False
        self.close()

    def close(self) -> None:
        if self._sink is not None:
            self._sink.cancel()
            self._sink = None
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class _ReadConn:
    """One framed connection issuing QC-anchored ledger reads
    (``TAG_STATE_READ``) against a node's replicated execution layer.

    Reads are NOT admission-controlled (the node answers at its last
    applied version without touching the ingest plane), so there is no
    credit window — just a FIFO of send timestamps matched to the
    in-order reply stream for round-trip latency."""

    def __init__(self, address):
        self.address = address
        self.writer: asyncio.StreamWriter | None = None
        self._sink: asyncio.Task | None = None
        self.alive = False
        self.sent = 0
        self.replies = 0
        self.found = 0
        self.version_max = 0
        self.latencies: list[float] = []
        self._pending: list[float] = []  # FIFO of send times

    async def connect(self) -> None:
        from hotstuff_tpu.network.framing import set_nodelay

        reader, writer = await asyncio.open_connection(*self.address)
        try:
            set_nodelay(writer)
            sink = asyncio.ensure_future(self._read_replies(reader))
        except BaseException:
            writer.close()
            raise
        self.writer = writer
        self._sink = sink
        self.alive = True
        self._pending.clear()

    def send_read(self, frame: bytes) -> None:
        from hotstuff_tpu.network.framing import write_frame

        if not self.alive:
            return
        try:
            write_frame(self.writer, frame)
        except (ConnectionError, OSError):
            self.mark_dead()
            return
        self.sent += 1
        self._pending.append(asyncio.get_running_loop().time())

    async def _read_replies(self, reader: asyncio.StreamReader) -> None:
        from hotstuff_tpu.consensus.wire import decode_state_value
        from hotstuff_tpu.network.framing import read_frame

        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await read_frame(reader)
                sv = decode_state_value(frame)
                if sv is None:
                    continue
                self.replies += 1
                if self._pending:
                    lat = loop.time() - self._pending.pop(0)
                    if len(self.latencies) < 10_000:
                        self.latencies.append(lat)
                if sv.found:
                    self.found += 1
                self.version_max = max(self.version_max, sv.state_version)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            self.mark_dead()

    def mark_dead(self) -> None:
        self.alive = False
        self.close()

    def close(self) -> None:
        if self._sink is not None:
            self._sink.cancel()
            self._sink = None
        if self.writer is not None:
            self.writer.close()
            self.writer = None


async def run_load(
    addresses,
    rate: int,
    duration: float,
    clients: int = 64,
    conns_per_node: int = 2,
    size: int = 512,
    seed: int = 1,
    warmup: float = 0.0,
    expect_faults: int = 0,
    read_fraction: float = 0.0,
) -> dict:
    """Drive a Poisson arrival process at ``rate`` tx/s for ``duration``
    seconds over ``conns_per_node`` connections to each node, honoring
    per-connection admission credits.  With ``read_fraction > 0`` each
    arrival is a LEDGER READ with that probability instead of a write:
    a ``TAG_STATE_READ`` round-trip against a recently written payload
    digest, answered at the node's last applied state version (a
    lagging node serves a QC-anchored stale read — the miss/hit split
    and the version spread are the measurement).  Returns the stats
    dict that is also written to the log as the ``Load stats:``
    contract line."""
    from hotstuff_tpu.consensus.wire import (
        MAX_PRODUCER_BATCH,
        STATE_READ_LEDGER,
        encode_producer_batch,
        encode_state_read,
    )
    from hotstuff_tpu.crypto import Digest
    from hotstuff_tpu.node.client import wait_for_nodes

    log.info("Waiting for all nodes to be online...")
    boot_timeout = max(15.0, 3.0 * len(addresses))
    live_addrs = await wait_for_nodes(
        addresses, timeout=boot_timeout, expect_faults=expect_faults
    )
    if not live_addrs:
        log.error("No nodes reachable")
        return {}
    if warmup:
        await asyncio.sleep(warmup)

    conns = [
        _LoadConn(a) for a in live_addrs for _ in range(conns_per_node)
    ]
    # one dedicated read connection per node — read replies must not
    # interleave with the write plane's credit-bearing ingest ACKs
    read_conns = (
        [_ReadConn(a) for a in live_addrs] if read_fraction > 0 else []
    )
    for c in conns + read_conns:
        try:
            await asyncio.wait_for(c.connect(), 2.0)
        except (OSError, asyncio.TimeoutError):
            log.warning(
                "Node %s:%d refused the connection; will retry", *c.address
            )

    async def reconnector() -> None:
        while True:
            await asyncio.sleep(2.0)
            for c in conns + read_conns:
                if not c.alive:
                    try:
                        await asyncio.wait_for(c.connect(), 1.5)
                        log.info("Reconnected to %s:%d", *c.address)
                    except (OSError, asyncio.TimeoutError):
                        pass

    reconnect_task = asyncio.ensure_future(reconnector())

    rng = random.Random(seed)
    sample_every = max(1, rate // SAMPLE_TARGET_PER_S)
    log.info("Start sending transactions")
    # NOTE: these log entries are used to compute performance.
    log.info("Transactions rate: %d tx/s", rate)
    log.info("Transactions size: %d B", size)
    log.info(
        "Virtual clients: %d over %d connection(s)",
        clients,
        len(conns),
    )
    if read_fraction > 0:
        log.info("Read fraction: %.2f", read_fraction)

    loop = asyncio.get_running_loop()
    start = loop.time()
    next_arrival = start + rng.expovariate(rate)
    offered = submitted = client_shed = counter = 0
    rr = 0  # connection rotation cursor across ticks
    reads_offered = read_rr = 0
    recent: list = []  # recently written digests, the read working set
    try:
        while True:
            now = loop.time()
            if now - start >= duration:
                break
            # arrivals whose Poisson timestamp has passed are due NOW;
            # the duration bound applies to the timestamps so the
            # offered count matches rate*duration in expectation
            due = 0
            while next_arrival <= now and next_arrival - start < duration:
                due += 1
                next_arrival += rng.expovariate(rate)
            # a read needs a working set — until the first write lands,
            # every arrival stays a write
            if due and read_conns and recent:
                reads_due = sum(
                    1 for _ in range(due) if rng.random() < read_fraction
                )
                due -= reads_due
                reads_offered += reads_due
                live_readers = [r for r in read_conns if r.alive]
                for _ in range(reads_due):
                    if not live_readers:
                        break
                    target = live_readers[read_rr % len(live_readers)]
                    read_rr += 1
                    digest = recent[rng.randrange(len(recent))]
                    target.send_read(
                        encode_state_read(STATE_READ_LEDGER, digest)
                    )
            if due:
                offered += due
                eligible = [
                    c
                    for c in conns
                    if c.alive and c.credit > 0 and now >= c.busy_until
                ]
                # round-robin the due arrivals over the eligible
                # connections (rotated each tick so no node is first
                # forever), bounded by each one's remaining credit —
                # whatever cannot be placed is open-loop client shed
                if eligible:
                    off = rr % len(eligible)
                    order = eligible[off:] + eligible[:off]
                    rr += 1
                else:
                    order = []
                budgets = [c.credit for c in order]
                batches: list[list] = [[] for _ in order]
                placed = k = misses = 0
                while placed < due and order:
                    i = k % len(order)
                    k += 1
                    if budgets[i] <= 0:
                        misses += 1
                        if misses >= len(order):
                            break  # every connection out of credit
                        continue
                    misses = 0
                    body = counter.to_bytes(8, "big") + os.urandom(
                        max(0, size - 8)
                    )
                    digest = Digest.of(body)
                    if counter % sample_every == 0:
                        # NOTE: used to compute performance.
                        log.info("Sending sample payload %s", digest)
                    batches[i].append((digest, body))
                    if read_conns:
                        recent.append(digest.to_bytes())
                        if len(recent) > 1024:
                            del recent[:512]
                    budgets[i] -= 1
                    counter += 1
                    placed += 1
                client_shed += due - placed
                for i, c in enumerate(order):
                    for lo in range(0, len(batches[i]), MAX_PRODUCER_BATCH):
                        chunk = batches[i][lo : lo + MAX_PRODUCER_BATCH]
                        c.send_batch(
                            encode_producer_batch(chunk), len(chunk)
                        )
                        submitted += len(chunk)
                for i, c in enumerate(order):
                    if batches[i]:
                        await c.drain()
            await asyncio.sleep(
                max(0.0, min(TICK, next_arrival - loop.time()))
            )
    finally:
        reconnect_task.cancel()
        # reads in flight when the window closes would miss their
        # replies — give the in-order streams a moment to drain
        if read_conns and any(r._pending for r in read_conns):
            await asyncio.sleep(0.25)
        for c in conns + read_conns:
            c.close()

    window = loop.time() - start
    stats = {
        "rate": rate,
        "clients": clients,
        "connections": len(conns),
        "window_s": round(window, 2),
        "offered": offered,
        "submitted": submitted,
        "accepted": sum(c.accepted for c in conns),
        "shed_server": sum(c.shed for c in conns),
        "shed_client": client_shed,
        "busy_frames": sum(c.busy_frames for c in conns),
    }
    if read_conns:
        lat = sorted(
            x for r in read_conns for x in r.latencies
        )
        stats["reads"] = {
            "fraction": read_fraction,
            "offered": reads_offered,
            "sent": sum(r.sent for r in read_conns),
            "replies": sum(r.replies for r in read_conns),
            "found": sum(r.found for r in read_conns),
            "version_max": max(
                (r.version_max for r in read_conns), default=0
            ),
            "p50_ms": (
                round(lat[len(lat) // 2] * 1e3, 2) if lat else None
            ),
        }
    # NOTE: this log entry is used to compute performance.
    log.info("Load stats: %s", json.dumps(stats))
    return stats


# ---- harness side -----------------------------------------------------------


def scrape_load_stats(client_log: str) -> dict:
    """The fleet's ``Load stats:`` document from a client log, or {}."""
    matches = RE_LOAD_STATS.findall(client_log)
    if not matches:
        return {}
    try:
        return json.loads(matches[-1])
    except ValueError:
        return {}


def scrape_ingest(telemetry_docs) -> dict:
    """Committee-wide ingest counters summed over the per-node
    telemetry snapshots (the ``ingest`` section each node exports)."""
    out = {
        "accepted_total": 0,
        "shed_total": 0,
        "busy_frames": 0,
        "drop_newest": 0,
    }
    seen = False
    for doc in telemetry_docs:
        section = doc.get("ingest")
        if not isinstance(section, dict):
            continue
        seen = True
        for key in out:
            out[key] += int(section.get(key, 0) or 0)
    out["present"] = seen
    return out


class LoadBench:
    """One committee run with the credit-aware fleet as the client.

    Composition over the LocalBench subclass hook: builds a LocalBench,
    swaps its ``_client_cmd`` for the fleet's, forces telemetry on in
    every node (the ``ingest`` snapshot section is the measurement),
    and optionally pins the proposer buffer cap so short runs can
    actually reach the shed watermark."""

    def __init__(
        self,
        nodes: int = 4,
        rate: int = 1_000,
        duration: float = 10.0,
        clients: int = 64,
        conns_per_node: int = 2,
        tx_size: int = 512,
        seed: int = 1,
        max_pending: int | None = None,
        timeout_delay: int = 5_000,
        verifier: str = "cpu",
        base_port: int | None = None,
        read_fraction: float = 0.0,
    ):
        from .local import LocalBench

        kwargs = dict(
            nodes=nodes,
            rate=rate,
            duration=duration,
            timeout_delay=timeout_delay,
            verifier=verifier,
            tx_size=tx_size,
        )
        if base_port is not None:
            kwargs["base_port"] = base_port
        self.bench = LocalBench(**kwargs)
        self.clients = clients
        self.conns_per_node = conns_per_node
        self.seed = seed
        self.read_fraction = read_fraction
        self.bench.extra_env["HOTSTUFF_TELEMETRY"] = "1"
        if max_pending is not None:
            self.bench.extra_env["HOTSTUFF_MAX_PENDING"] = str(max_pending)
        self.bench._client_cmd = self._client_cmd  # the hook

    def _client_cmd(self, py: str) -> list[str]:
        from .utils import PathMaker

        b = self.bench
        return [
            py,
            "-m",
            "benchmark.loadgen",
            "--committee",
            PathMaker.committee_file(),
            "--rate",
            str(b.rate),
            "--duration",
            str(b.duration),
            "--size",
            str(b.tx_size),
            "--clients",
            str(self.clients),
            "--conns",
            str(self.conns_per_node),
            "--seed",
            str(self.seed),
            "--warmup",
            "2",
            "--faults",
            str(b.faults),
            "--read-fraction",
            str(self.read_fraction),
        ]

    def run(self) -> dict:
        """Run the committee and return one sweep row:
        offered/goodput/shed/latency plus the committee ingest
        counters."""
        import glob

        from .utils import PathMaker

        parser = self.bench.run()
        client_log = ""
        for path in sorted(
            glob.glob(os.path.join(PathMaker.logs_path(), "client*.log"))
        ):
            with open(path) as f:
                client_log += f.read()
        fleet = scrape_load_stats(client_log)
        ingest = scrape_ingest(parser.telemetry_docs)
        goodput, _window = parser.consensus_throughput()
        pcts = parser.end_to_end_latency_percentiles()
        return {
            "offered_tx_s": self.bench.rate,
            "goodput_tx_s": round(goodput, 1),
            "delivered_tx_s": (
                round(fleet["submitted"] / fleet["window_s"], 1)
                if fleet.get("window_s")
                else None
            ),
            "client_p50_ms": (
                round(pcts[0] * 1e3, 1) if pcts is not None else None
            ),
            "client_p99_ms": (
                round(pcts[1] * 1e3, 1) if pcts is not None else None
            ),
            "shed_server": ingest["shed_total"],
            "shed_client": fleet.get("shed_client", 0),
            "busy_frames": ingest["busy_frames"],
            "drop_newest": ingest["drop_newest"],
            "telemetry_present": ingest["present"],
            "fleet": fleet,
            **(
                {"reads": fleet["reads"]} if fleet.get("reads") else {}
            ),
        }


def run_sweep(
    nodes: int = 4,
    start_rate: int = 500,
    duration: float = 10.0,
    max_steps: int = 6,
    clients: int = 64,
    conns_per_node: int = 2,
    tx_size: int = 512,
    seed: int = 1,
    overload_max_pending: int = 2_000,
    plateau_gain: float = 0.10,
    read_fraction: float = 0.0,
) -> dict:
    """Saturation sweep: double the offered rate until goodput improves
    by less than ``plateau_gain`` (or ``max_steps`` runs), then drive
    2x the saturation rate against a small proposer buffer
    (``overload_max_pending``) and record the backpressure verdict."""
    from .utils import Print

    rows: list[dict] = []
    rate = start_rate
    best = 0.0
    for step in range(max_steps):
        Print.info(f"load sweep step {step + 1}: {rate} tx/s offered")
        row = LoadBench(
            nodes=nodes,
            rate=rate,
            duration=duration,
            clients=clients,
            conns_per_node=conns_per_node,
            tx_size=tx_size,
            seed=seed,
            read_fraction=read_fraction,
        ).run()
        rows.append(row)
        goodput = row["goodput_tx_s"] or 0.0
        if step > 0 and goodput < best * (1.0 + plateau_gain):
            break
        best = max(best, goodput)
        rate *= 2

    # saturation = the offered rate of the best-goodput row (the
    # plateau's left edge, not the overshot last step)
    sat_row = max(rows, key=lambda r: r["goodput_tx_s"] or 0.0)
    saturation = sat_row["offered_tx_s"]

    overload_rate = 2 * saturation
    Print.info(
        f"overload step: {overload_rate} tx/s offered "
        f"(2x saturation, max-pending {overload_max_pending})"
    )
    overload = LoadBench(
        nodes=nodes,
        rate=overload_rate,
        duration=duration,
        clients=clients,
        conns_per_node=conns_per_node,
        tx_size=tx_size,
        seed=seed,
        max_pending=overload_max_pending,
        read_fraction=read_fraction,
    ).run()
    sheds = overload["shed_server"] + overload["shed_client"]
    overload["backpressure_held"] = (
        overload["drop_newest"] == 0 and sheds > 0
    )
    return {
        "nodes": nodes,
        "clients": clients,
        "conns_per_node": conns_per_node,
        "duration_s": duration,
        "rows": rows,
        "saturation_tx_s": saturation,
        "overload": overload,
        "goodput_tx_s": sat_row["goodput_tx_s"],
        "client_p50_ms": sat_row["client_p50_ms"],
        "client_p99_ms": sat_row["client_p99_ms"],
        **(
            {"reads": sat_row["reads"]} if sat_row.get("reads") else {}
        ),
    }


def format_load_block(result: dict) -> str:
    """The ``+ LOAD`` SUMMARY block for a sweep result."""

    def txt(v, unit=""):
        return f"{v}{unit}" if v is not None else "n/a"

    lines = [
        " + LOAD:",
        f" Committee size: {result['nodes']} node(s)",
        f" Virtual clients: {result['clients']} over"
        f" {result['conns_per_node']} connection(s)/node",
        f" Step duration: {result['duration_s']:.0f} s",
        f" Saturation: ~{result['saturation_tx_s']} tx/s offered"
        " (goodput plateau)",
        "",
        "  offered tx/s  goodput tx/s  shed/s  p50 ms  p99 ms",
    ]
    for row in result["rows"]:
        shed = row["shed_server"] + row["shed_client"]
        shed_s = round(shed / result["duration_s"], 1) if shed else 0
        lines.append(
            f"  {row['offered_tx_s']:>12}"
            f"  {txt(row['goodput_tx_s']):>12}"
            f"  {shed_s:>6}"
            f"  {txt(row['client_p50_ms']):>6}"
            f"  {txt(row['client_p99_ms']):>6}"
        )
    o = result["overload"]
    verdict = (
        "backpressure HELD (sheds observed, zero silent drop-newest)"
        if o["backpressure_held"]
        else "backpressure verdict: "
        + (
            f"drop_newest={o['drop_newest']} (silent drops!)"
            if o["drop_newest"]
            else "no sheds observed (offered rate below the watermark)"
        )
    )
    reads = result.get("reads")
    if reads:
        lines += [
            "",
            f" Mixed reads ({reads['fraction']:.0%} of arrivals):"
            f" {reads['sent']} sent, {reads['replies']} answered,"
            f" {reads['found']} found,"
            f" p50 {txt(reads['p50_ms'], ' ms')},"
            f" served at state version <= {reads['version_max']}",
        ]
    lines += [
        "",
        f" Overload (2x saturation = {o['offered_tx_s']} tx/s):",
        f" Goodput: {txt(o['goodput_tx_s'])} tx/s,"
        f" shed {o['shed_server']} (server) + {o['shed_client']} (client),"
        f" busy frames {o['busy_frames']}",
        f" Proposer drop-newest: {o['drop_newest']} — {verdict}",
    ]
    return "\n".join(lines) + "\n"


def quick_load(
    nodes: int = 4,
    rate: int = 2_000,
    duration: float = 10.0,
    max_pending: int | None = None,
    read_fraction: float = 0.0,
) -> dict:
    """One fixed-rate run for the bench.py ``load`` block / perfgate
    guards: goodput + client percentiles without the full sweep."""
    row = LoadBench(
        nodes=nodes, rate=rate, duration=duration, max_pending=max_pending,
        read_fraction=read_fraction,
    ).run()
    return {
        "offered_tx_s": row["offered_tx_s"],
        "goodput_tx_s": row["goodput_tx_s"],
        "client_p50_ms": row["client_p50_ms"],
        "client_p99_ms": row["client_p99_ms"],
        "shed_server": row["shed_server"],
        "shed_client": row["shed_client"],
        "drop_newest": row["drop_newest"],
        **({"reads": row["reads"]} if row.get("reads") else {}),
    }


# ---- fleet CLI (the client process LoadBench spawns) ------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Credit-aware open-loop load-generator fleet"
    )
    parser.add_argument("--committee", required=True)
    parser.add_argument("--rate", type=int, default=1_000)
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--size", type=int, default=512)
    parser.add_argument("--clients", type=int, default=64)
    parser.add_argument(
        "--conns", type=int, default=2, help="connections per node"
    )
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--warmup", type=float, default=2.0)
    parser.add_argument("--faults", type=int, default=0)
    parser.add_argument(
        "--read-fraction",
        type=float,
        default=0.0,
        help="probability each arrival is a QC-anchored ledger read "
        "instead of a write (0 = pure write fleet)",
    )
    parser.add_argument("-v", "--verbose", action="count", default=1)
    args = parser.parse_args(argv)

    logging.basicConfig(
        level=[logging.ERROR, logging.INFO, logging.DEBUG][
            min(args.verbose, 2)
        ],
        format="%(asctime)s.%(msecs)03dZ [%(levelname)s] %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
    )

    from hotstuff_tpu.consensus.wire import MAX_PAYLOAD_BODY
    from hotstuff_tpu.node.config import read_committee

    if not 8 <= args.size <= MAX_PAYLOAD_BODY:
        parser.error(
            f"--size must be in [8, {MAX_PAYLOAD_BODY}] (the 8-byte "
            "uniqueness counter rides every body)"
        )
    if args.rate < 1 or args.conns < 1 or args.clients < 1:
        parser.error("--rate, --conns and --clients must be >= 1")
    if not 0.0 <= args.read_fraction < 1.0:
        parser.error("--read-fraction must be in [0, 1)")
    committee = read_committee(args.committee)
    addresses = [a.address for a in committee.authorities.values()]
    asyncio.run(
        run_load(
            addresses,
            args.rate,
            args.duration,
            clients=args.clients,
            conns_per_node=args.conns,
            size=args.size,
            seed=args.seed,
            warmup=args.warmup,
            expect_faults=args.faults,
            read_fraction=args.read_fraction,
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
